"""Device-native SHAP (``pred_contrib``) through the engine and the
serving stack (docs/perf.md "Device SHAP"; docs/serving.md "Mixed
predict + explain workloads").

What these tests pin:

* **Exactness** — the engine path (path-table cache + bucketed chunked
  dispatch) is f64-EXACT on CPU against the host rows-vectorized
  ``forest_shap_batch`` across binary / multiclass / categorical /
  NaN forests and ``num_iteration`` slices (the host path is itself
  pinned to the per-row recursive oracle in test_shap_vectorized.py).
* **Zero warm compiles** — after one call at a bucket, SHAP at any
  request size inside warmed buckets compiles ZERO XLA programs
  (CompileWatch), the same pow2-bucket guarantee predict carries.
* **Path-table cache** — hits counted, invalidated by forest growth,
  never shared across ``num_iteration`` slices.
* **Tree sharding** — the ``shard_map``+psum scan over 2- and 8-device
  tree meshes matches the unsharded result to f64 reassociation
  tolerance, gated by ``capabilities.SHARDED_SHAP`` (DART and
  linear-tree configs demote to the host path with a warned
  stand-down, never a refusal).
* **(model, kind) queue lanes** — explain riders never coalesce into
  predict batches; served contributions are exact.
"""
import threading

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import capabilities, obs
from lightgbm_tpu.serve import PredictService
from lightgbm_tpu.serve.shard import enable_tree_sharding, tree_mesh
from lightgbm_tpu.utils.debug import CompileWatch

pytestmark = pytest.mark.filterwarnings("ignore")


@pytest.fixture(autouse=True)
def _isolated_obs():
    obs.disable()
    obs.reset()
    yield
    obs.disable()
    obs.reset()


def _train(n=2000, f=8, with_cat=False, with_nan=False, seed=0,
           num_leaves=15, rounds=8, objective="regression", **extra):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    logit = X[:, 0] * 1.2 - 0.8 * X[:, 1] + 0.5 * X[:, 2] * X[:, 3]
    cat_idx = []
    if with_cat:
        c = rng.integers(0, 9, size=n)
        X[:, f - 1] = c
        logit = logit + np.where(c % 3 == 0, 1.0, -0.4)
        cat_idx = [f - 1]
    if with_nan:
        miss = rng.uniform(size=n) < 0.15
        X[miss, 0] = np.nan
    if objective == "binary":
        y = (logit + rng.normal(scale=0.5, size=n) > 0).astype(float)
    elif objective == "multiclass":
        y = rng.integers(0, 3, size=n).astype(float)
    else:
        y = logit + rng.normal(scale=0.3, size=n)
    params = {"objective": objective, "num_leaves": num_leaves,
              "verbosity": -1, **extra}
    if objective == "multiclass":
        params["num_class"] = 3
    bst = lgb.train(params, lgb.Dataset(X, label=y,
                                        categorical_feature=cat_idx),
                    num_boost_round=rounds)
    return bst, X


# ---------------------------------------------------------------------------
# exactness vs the host path
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("with_cat,with_nan,objective", [
    (False, False, "regression"),
    (True, False, "regression"),
    (False, True, "binary"),
    (True, True, "binary"),
    (False, False, "multiclass"),
])
def test_engine_matches_host(with_cat, with_nan, objective):
    bst, X = _train(with_cat=with_cat, with_nan=with_nan,
                    objective=objective)
    got = bst.predict(X[:300], pred_contrib=True)
    want = bst._to_host_model().predict(X[:300], pred_contrib=True)
    # CPU backend: both sides run the same f64 kernel; the engine pads
    # rows to its pow2 bucket, which is allowed to move XLA's
    # vectorization by one ULP — nothing more
    np.testing.assert_allclose(got, want, rtol=0, atol=1e-12)


def test_num_iteration_slices_match_host():
    bst, X = _train(with_cat=True, with_nan=True, objective="binary",
                    rounds=10)
    hm = bst._to_host_model()
    for kw in ({"num_iteration": 4}, {"start_iteration": 3},
               {"start_iteration": 2, "num_iteration": 5}):
        got = bst.predict(X[:200], pred_contrib=True, **kw)
        want = hm.predict(X[:200], pred_contrib=True, **kw)
        np.testing.assert_allclose(got, want, rtol=0, atol=1e-12)


def test_local_accuracy_multiclass():
    bst, X = _train(objective="multiclass", rounds=6)
    n_feat = X.shape[1]
    contrib = bst.predict(X[:200], pred_contrib=True)
    raw = bst.predict(X[:200], raw_score=True)
    per_class = contrib.reshape(len(raw), 3, n_feat + 1).sum(axis=2)
    # raw predictions ride the f32 device path; SHAP sums are f64
    np.testing.assert_allclose(per_class, raw, rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# compile discipline + the path-table cache
# ---------------------------------------------------------------------------
def test_zero_warm_compiles_across_sizes():
    bst, X = _train(rounds=6)
    for n in (128, 256):            # warm both pow2 buckets the sizes
        bst.predict(X[:n], pred_contrib=True)     # below land in
    with CompileWatch("warm-shap") as w:
        for n in (1, 7, 64, 128, 200, 256):       # inside warm buckets
            bst.predict(X[:n], pred_contrib=True)
    w.assert_compiles(0)


def test_table_cache_hits_and_invalidation():
    bst, X = _train(rounds=6)
    obs.enable(metrics=True)
    eng = bst.engine

    def counter(name):
        m = obs.registry().get(name)
        return getattr(m, "value", 0.0) or 0.0

    bst.predict(X[:64], pred_contrib=True)
    assert counter("predict.contrib_cache_misses") == 1.0
    bst.predict(X[:64], pred_contrib=True)
    assert counter("predict.contrib_cache_hits") >= 1.0
    # a num_iteration slice is a different table set, never a hit
    bst.predict(X[:64], pred_contrib=True, num_iteration=3)
    assert counter("predict.contrib_cache_misses") == 2.0
    # forest growth/eviction drops the device tables with the stack
    eng._invalidate_forest_cache()
    assert eng._shap_cache is None
    misses = counter("predict.contrib_cache_misses")
    bst.predict(X[:64], pred_contrib=True)
    assert counter("predict.contrib_cache_misses") == misses + 1.0


def test_hostmodel_caches_path_tables_per_slice():
    bst, X = _train(rounds=8)
    hm = bst._to_host_model()
    a = hm.predict(X[:32], pred_contrib=True)
    cache = hm._shap_table_cache
    assert len(cache) == 1
    key, tables = next(iter(cache.items()))
    hm.predict(X[:32], pred_contrib=True, num_iteration=3)
    assert len(cache) == 2                 # slice = its own tables
    hm.predict(X[:32], pred_contrib=True)
    assert cache[key] is tables            # full-forest call reused
    np.testing.assert_array_equal(a, hm.predict(X[:32],
                                                pred_contrib=True))


# ---------------------------------------------------------------------------
# tree-sharded SHAP
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("devices", [2, 8])
def test_sharded_matches_unsharded(devices):
    bst, X = _train(with_cat=True, with_nan=True, objective="binary",
                    rounds=8)
    want = bst.predict(X[:200], pred_contrib=True)
    mesh = enable_tree_sharding(bst, tree_mesh(devices))
    assert mesh is not None
    assert bst.engine._predict_mesh is mesh
    got = bst.predict(X[:200], pred_contrib=True)
    # f64 on the CPU backend: the only difference is the psum's
    # reduction order across shards
    np.testing.assert_allclose(got, want, rtol=0, atol=1e-12)
    sliced = bst.predict(X[:200], pred_contrib=True, num_iteration=4)
    want_sliced = bst._to_host_model().predict(
        X[:200], pred_contrib=True, num_iteration=4)
    np.testing.assert_allclose(sliced, want_sliced, rtol=0, atol=1e-12)


# ---------------------------------------------------------------------------
# capability gate
# ---------------------------------------------------------------------------
def test_capability_verdicts():
    assert capabilities.sharded_shap_verdict("gbdt") \
        == capabilities.SUPPORTED
    for eng in ("dart", "rf", "streaming"):
        assert capabilities.sharded_shap_verdict(eng) \
            == capabilities.DEMOTE
        assert eng in capabilities.SHARDED_SHAP_MESSAGES

    class _Cfg:
        linear_tree = True
    assert capabilities.sharded_shap_verdict("gbdt", _Cfg()) \
        == capabilities.DEMOTE


def test_dart_demotes_to_host_path_with_one_warning():
    bst, X = _train(objective="binary", rounds=6, boosting="dart")
    got = bst.predict(X[:100], pred_contrib=True)
    want = bst._to_host_model().predict(X[:100], pred_contrib=True)
    np.testing.assert_array_equal(got, want)
    assert getattr(bst, "_warned_shap_demote", False)
    # the demoted engine never built device SHAP state
    assert getattr(bst.engine, "_shap_cache", None) is None


# ---------------------------------------------------------------------------
# serving: (model, kind) lanes
# ---------------------------------------------------------------------------
def test_service_explain_lanes_never_coalesce_with_predicts():
    bst, X = _train(rounds=4, num_leaves=8)
    obs.enable(metrics=True)
    svc = PredictService({"tpu_serve_batch_budget_ms": 150.0,
                          "tpu_serve_max_batch_rows": 1024,
                          "tpu_serve_shard_trees": "false"})
    try:
        svc.add_model("m", bst)
        Xq = X[:64]
        futs = ([svc.submit("m", Xq) for _ in range(3)]
                + [svc.submit("m", Xq, kind="contrib")
                   for _ in range(3)])
        outs = [f.result(timeout=30) for f in futs]
        direct_p = bst.predict(Xq)
        direct_c = bst.predict(Xq, pred_contrib=True)
        for out in outs[:3]:
            np.testing.assert_array_equal(out, direct_p)
        for out in outs[3:]:
            # coalesced riders run at a bigger row bucket than the
            # direct call — ULP-only freedom, like engine-vs-host
            np.testing.assert_allclose(out, direct_c, rtol=0,
                                       atol=1e-12)
        reg = obs.registry()
        # one batch per lane: the 6 riders coalesced into exactly 2
        # kind-homogeneous dispatches, never a mixed batch
        assert reg.get("serve.dispatches").value == 2.0
        assert reg.get("serve.explain_requests").value == 3.0
        with pytest.raises(ValueError):
            svc.submit("m", Xq, kind="leaf")
    finally:
        svc.close()


def test_service_warmup_contrib_then_zero_compiles():
    bst, X = _train(rounds=4, num_leaves=8)
    svc = PredictService({"tpu_serve_batch_budget_ms": 2.0,
                          "tpu_serve_max_batch_rows": 512,
                          "tpu_serve_shard_trees": "false"})
    try:
        svc.add_model("m", bst)
        svc.warmup("m", X[:1], kinds=("predict", "contrib"))
        Xq = X[:96]
        with CompileWatch("warm-serve-shap") as w:
            out = svc.submit("m", Xq, kind="contrib").result(timeout=30)
            stop = threading.Event()
            stop.wait(0.01)
        w.assert_compiles(0)
        np.testing.assert_allclose(
            out, bst.predict(Xq, pred_contrib=True), rtol=0,
            atol=1e-12)
    finally:
        svc.close()
