"""Tree-sharded predict (serve/shard.py + ops/predict.py).

What these tests pin (on the conftest 8-fake-CPU-device mesh):

* **Bit-identity** — predicts with the stacked tree axis
  NamedSharding-split over a 2-device (and 8-device) mesh are
  ``array_equal`` to the single-device path: binary, multiclass
  (sequential class accumulation preserved), pred_leaf, raw_score,
  and num_iteration slices.
* **Warm path** — repeat sharded predicts re-place nothing and
  compile nothing (CompileWatch), and hot-swap under sharding stays
  zero-recompile.
* **Capability routing** — every engine has a SHARDED_PREDICT row;
  DART / streaming / linear_tree / model-file boosters DEMOTE to the
  unsharded path (enable returns None, serving continues).
* **Policy** — ``tpu_serve_shard_trees`` false/true/auto behave per
  docs/serving.md (auto gates on the shared HBM estimate).
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import capabilities, obs
from lightgbm_tpu.serve.shard import (auto_shard_mesh,
                                      enable_tree_sharding, tree_mesh)
from lightgbm_tpu.utils.debug import CompileWatch

pytestmark = pytest.mark.filterwarnings("ignore")


@pytest.fixture(autouse=True)
def _isolated_obs():
    obs.disable()
    obs.reset()
    yield
    obs.disable()
    obs.reset()


def _data(n=2500, f=10, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2] > 0).astype(np.float64)
    return X, y


BIN = {"objective": "binary", "num_leaves": 8, "verbosity": -1}


@pytest.fixture(scope="module")
def query():
    rng = np.random.default_rng(7)
    return rng.normal(size=(600, 10))


def test_bit_identical_binary_2_and_8_devices(query):
    X, y = _data()
    bst = lgb.train(BIN, lgb.Dataset(X, label=y), num_boost_round=7)
    base = bst.predict(query)
    base_raw = bst.predict(query, raw_score=True)
    base_leaf = bst.predict(query, pred_leaf=True)
    for d in (2, 8):
        mesh = enable_tree_sharding(bst, tree_mesh(d))
        assert mesh is not None and int(mesh.devices.size) == d
        np.testing.assert_array_equal(bst.predict(query), base)
        np.testing.assert_array_equal(
            bst.predict(query, raw_score=True), base_raw)
        np.testing.assert_array_equal(
            bst.predict(query, pred_leaf=True), base_leaf)


def test_bit_identical_multiclass_and_slices(query):
    X, _ = _data(seed=1)
    rng = np.random.default_rng(1)
    y = rng.integers(0, 3, size=len(X)).astype(np.float64)
    p = {"objective": "multiclass", "num_class": 3, "num_leaves": 8,
         "verbosity": -1}
    bst = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=5)
    base = bst.predict(query)
    base_slice = bst.predict(query, num_iteration=3)
    mesh = enable_tree_sharding(bst, tree_mesh(2))
    assert mesh is not None
    np.testing.assert_array_equal(bst.predict(query), base)
    # early-stop style slice: padded tree count stays mesh-divisible
    np.testing.assert_array_equal(bst.predict(query, num_iteration=3),
                                  base_slice)


def test_sharded_warm_path_compiles_nothing(query):
    X, y = _data(seed=2)
    bst = lgb.train(BIN, lgb.Dataset(X, label=y), num_boost_round=6)
    enable_tree_sharding(bst, tree_mesh(2))
    bst.predict(query)
    builds = bst.engine._stack_builds
    with CompileWatch("sharded-warm") as w:
        bst.predict(query)
    w.assert_compiles(0)
    assert bst.engine._stack_builds == builds   # cached sharded stack


def test_capability_rows_cover_every_engine():
    for eng in capabilities.ENGINES:
        assert eng in capabilities.SHARDED_PREDICT
        assert capabilities.SHARDED_PREDICT[eng] in (
            capabilities.SUPPORTED, capabilities.DEMOTE)
    assert capabilities.sharded_predict_verdict("gbdt") \
        == capabilities.SUPPORTED
    assert capabilities.sharded_predict_verdict("dart") \
        == capabilities.DEMOTE
    assert capabilities.sharded_predict_verdict("streaming") \
        == capabilities.DEMOTE
    # unknown engines demote (serve unsharded), never crash
    assert capabilities.sharded_predict_verdict("future_engine") \
        == capabilities.DEMOTE


def test_dart_and_linear_demote_unsharded():
    X, y = _data(seed=3)
    dart = lgb.train(dict(BIN, boosting="dart"),
                     lgb.Dataset(X, label=y), num_boost_round=4)
    assert enable_tree_sharding(dart, tree_mesh(2)) is None
    assert dart.engine._predict_mesh is None

    lin = lgb.train(dict(BIN, linear_tree=True),
                    lgb.Dataset(X, label=y), num_boost_round=3)
    assert capabilities.sharded_predict_verdict(
        "gbdt", lin.engine.config) == capabilities.DEMOTE
    assert enable_tree_sharding(lin, tree_mesh(2)) is None


def test_model_file_booster_demotes():
    X, y = _data(seed=4)
    bst = lgb.train(BIN, lgb.Dataset(X, label=y), num_boost_round=3)
    loaded = lgb.Booster(model_str=bst.model_to_string())
    assert enable_tree_sharding(loaded, tree_mesh(2)) is None
    # ... and still predicts (host-model path)
    assert loaded.predict(X[:16]).shape == (16,)


def test_registry_cache_hits_under_sharding(query):
    """Shard enablement bumps the model version ONCE: re-applying the
    policy (every LRU admission runs it) must be a no-op, so warm
    checkouts are cache HITS, not an endless re-stack/re-upload
    admission loop (the smoke runs unsharded — pin it here)."""
    from lightgbm_tpu.serve import ModelRegistry
    obs.enable(metrics=True)
    X, y = _data(seed=6)
    bst = lgb.train(BIN, lgb.Dataset(X, label=y), num_boost_round=4)
    reg = ModelRegistry({"tpu_serve_shard_trees": "true"})
    reg.register("m", bst)
    assert bst.engine._predict_mesh is not None
    ver = bst.engine._models_version
    reg.checkout("m").predict(query)
    reg.checkout("m")
    reg.checkout("m")
    assert bst.engine._models_version == ver    # policy re-runs: no-op
    assert obs.registry().get("serve.cache_hits").value == 2.0
    builds = bst.engine._stack_builds
    reg.checkout("m").predict(query)            # warm: cached stack
    assert bst.engine._stack_builds == builds


def test_policy_knob_false_true_auto(monkeypatch, query):
    X, y = _data(seed=5)
    from lightgbm_tpu.config import Config
    bst = lgb.train(BIN, lgb.Dataset(X, label=y), num_boost_round=4)
    assert auto_shard_mesh(
        bst, Config({"tpu_serve_shard_trees": "false"})) is None
    assert bst.engine._predict_mesh is None

    # auto with no reported HBM limit: stay unsharded
    from lightgbm_tpu.serve import shard as shard_mod
    monkeypatch.setattr(shard_mod, "hbm_bytes_limit", lambda: None)
    assert auto_shard_mesh(
        bst, Config({"tpu_serve_shard_trees": "auto"})) is None

    # auto with a tiny mocked limit: the estimate exceeds the fraction
    monkeypatch.setattr(shard_mod, "hbm_bytes_limit", lambda: 64)
    mesh = auto_shard_mesh(
        bst, Config({"tpu_serve_shard_trees": "auto"}))
    assert mesh is not None
    np.testing.assert_array_equal(bst.predict(query),
                                  bst.predict(query))

    bst2 = lgb.train(BIN, lgb.Dataset(X, label=y), num_boost_round=4)
    base = bst2.predict(query)
    mesh = auto_shard_mesh(
        bst2, Config({"tpu_serve_shard_trees": "true"}))
    assert mesh is not None
    np.testing.assert_array_equal(bst2.predict(query), base)
