"""Serving-grade inference engine tests (tree-parallel traversal,
stacked-forest caching, batch-shape bucketing — ops/predict.py +
GBDT.predict).

The contracts pinned here:
1. the level-synchronous tree-parallel traversal is BIT-IDENTICAL to
   the reference per-tree scan on every model family (numerical with
   NaNs, categorical bitsets, multiclass round-robin, DART, RF
   averaging, pred_leaf) in both level-step formulations;
2. bucketed/padded/chunked predict == unpadded predict for ragged
   batch sizes;
3. repeat predicts on an unchanged model do ZERO host-side tree
   stacking, ZERO forest re-uploads, and ZERO fresh XLA compiles
   (the serving steady state);
4. num_iteration/start_iteration slices share bucketed stack shapes
   instead of compiling per slice.
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.ops.predict import (forest_predict_binned,
                                      predict_program_cache_size)
from lightgbm_tpu.utils.debug import CompileWatch


def _data(n=1200, f=8, seed=0, nan_frac=0.05, n_cat=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    w = rng.normal(size=f)
    logit = X @ w + 0.6 * X[:, 0] * X[:, 1]
    if nan_frac:
        X[rng.random((n, f)) < nan_frac] = np.nan
    cats = []
    for c in range(n_cat):
        cv = rng.integers(0, 10 + 6 * c, size=n)
        logit = logit + rng.normal(size=10 + 6 * c)[cv]
        cats.append(cv.astype(np.float64))
    if cats:
        X = np.column_stack([X] + cats)
    y = (logit + rng.normal(scale=0.5, size=n) > 0).astype(np.float64)
    return X, y


def _train(params, X, y, rounds=10, cat="auto"):
    ds = lgb.Dataset(X, label=y, categorical_feature=cat)
    p = {"verbosity": -1, **params}
    return lgb.train(p, ds, num_boost_round=rounds)


_FIXTURES = None


def _fixture_boosters():
    """One booster per model family the traversal must cover (built
    once per test process; tests must restore any config they toggle)."""
    global _FIXTURES
    if _FIXTURES is not None:
        return _FIXTURES
    X, y = _data()
    Xc, yc = _data(seed=3, n_cat=2)
    rng = np.random.default_rng(5)
    ym = rng.integers(0, 3, size=len(X)).astype(np.float64)
    _FIXTURES = [
        ("binary+nan", X,
         _train({"objective": "binary", "num_leaves": 15}, X, y)),
        ("categorical", Xc,
         _train({"objective": "binary", "num_leaves": 15}, Xc, yc,
                cat=[8, 9])),
        ("multiclass", X,
         _train({"objective": "multiclass", "num_class": 3,
                 "num_leaves": 7}, X, ym)),
        ("dart", X,
         _train({"objective": "regression", "boosting": "dart",
                 "num_leaves": 15, "drop_rate": 0.5, "skip_drop": 0.0},
                X, y, rounds=8)),
        ("rf", X,
         _train({"objective": "binary", "boosting": "rf",
                 "num_leaves": 15, "bagging_freq": 1,
                 "bagging_fraction": 0.6}, X, y, rounds=8)),
    ]
    return _FIXTURES


# ---------------------------------------------------------------------------
# 1. bit-exactness of the tree-parallel traversal vs the per-tree scan
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("formulation", ["gather", "onehot"])
def test_level_sync_bit_identical_to_scan(formulation):
    import jax.numpy as jnp
    for name, X, bst in _fixture_boosters():
        eng = bst.engine
        stacked, ci = eng._stack_models(0, len(eng.models))
        bins = jnp.asarray(eng.train_set._bin_all_columns(
            lgb.Dataset._to_matrix(X), False, eng.train_set.binned.dtype,
            n_rows=len(X)))
        s0, l0 = forest_predict_binned(
            stacked, bins, eng.feat_num_bin, eng.feat_has_nan, ci,
            eng.num_class, mode="scan")
        s1, l1 = forest_predict_binned(
            stacked, bins, eng.feat_num_bin, eng.feat_has_nan, ci,
            eng.num_class, mode="level", formulation=formulation)
        assert np.array_equal(np.asarray(l0), np.asarray(l1)), \
            f"{name}: leaf routing diverged ({formulation})"
        assert np.array_equal(np.asarray(s0), np.asarray(s1)), \
            f"{name}: scores diverged ({formulation})"


def test_booster_predict_level_vs_scan_end_to_end():
    """Full predict() pipeline equality, incl. pred_leaf, under the
    tpu_predict_parallel_trees escape hatch."""
    for name, X, bst in _fixture_boosters():
        eng = bst.engine
        p1 = bst.predict(X)
        l1 = bst.predict(X, pred_leaf=True)
        eng.config.tpu_predict_parallel_trees = False
        p0 = bst.predict(X)
        l0 = bst.predict(X, pred_leaf=True)
        eng.config.tpu_predict_parallel_trees = True
        assert np.array_equal(p0, p1), name
        assert np.array_equal(l0, l1), name


# ---------------------------------------------------------------------------
# 2. bucketing / padding / chunking never changes results
# ---------------------------------------------------------------------------

def test_bucketed_predict_equals_unpadded():
    X, y = _data(n=2100)
    bst = _train({"objective": "binary", "num_leaves": 15}, X, y)
    eng = bst.engine
    for n in (1, 2, 5, 127, 128, 129, 1000, 2100):
        padded = bst.predict(X[:n], raw_score=True)
        eng.config.tpu_predict_buckets = False
        exact = bst.predict(X[:n], raw_score=True)
        eng.config.tpu_predict_buckets = True
        assert padded.shape[0] == n
        assert np.array_equal(padded, exact), n


def test_chunked_predict_equals_single_pass():
    X, y = _data(n=3000)
    bst = _train({"objective": "multiclass", "num_class": 3,
                  "num_leaves": 7}, X,
                 np.random.default_rng(1).integers(
                     0, 3, size=3000).astype(np.float64))
    eng = bst.engine
    eng.config.tpu_predict_chunk_rows = 1024   # 3 chunks, last padded
    chunked = bst.predict(X)
    chunked_leaf = bst.predict(X, pred_leaf=True)
    eng.config.tpu_predict_chunk_rows = 65536
    single = bst.predict(X)
    single_leaf = bst.predict(X, pred_leaf=True)
    assert np.array_equal(chunked, single)
    assert np.array_equal(chunked_leaf, single_leaf)


def test_num_iteration_slices_match_legacy():
    X, y = _data(n=900)
    bst = _train({"objective": "binary", "num_leaves": 15}, X, y,
                 rounds=12)
    eng = bst.engine
    for start, num in ((0, 3), (2, 5), (5, -1), (0, 12)):
        a = bst.predict(X, raw_score=True, start_iteration=start,
                        num_iteration=num)
        eng.config.tpu_predict_parallel_trees = False
        eng.config.tpu_predict_buckets = False
        b = bst.predict(X, raw_score=True, start_iteration=start,
                        num_iteration=num)
        eng.config.tpu_predict_parallel_trees = True
        eng.config.tpu_predict_buckets = True
        assert np.array_equal(a, b), (start, num)


# ---------------------------------------------------------------------------
# 3. the serving steady state: zero stacking / uploads / compiles
# ---------------------------------------------------------------------------

def test_second_predict_zero_stacking_and_zero_compiles():
    X, y = _data(n=800)
    bst = _train({"objective": "binary", "num_leaves": 15}, X, y)
    eng = bst.engine

    bst.predict(X[:500])               # warms stack + the 512 bucket
    s1, c1 = eng._stack_for_predict(0, len(eng.models))
    s2, c2 = eng._stack_for_predict(0, len(eng.models))
    assert s1 is s2 and c1 is c2       # device stack reused, not rebuilt

    builds_before = eng._stack_builds
    with CompileWatch() as watch:
        p1 = bst.predict(X[:500])
        p2 = bst.predict(X[:500])
    # warm serving steady state: zero host-side tree stacking (and thus
    # zero forest re-uploads — upload happens inside the build), zero
    # fresh XLA programs
    assert eng._stack_builds == builds_before
    s3, _ = eng._stack_for_predict(0, len(eng.models))
    assert s3 is s1
    assert watch.compiles == 0, watch.events
    assert np.array_equal(p1, p2)


def test_model_growth_invalidates_stack_cache():
    X, y = _data(n=600)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1}, ds, num_boost_round=5,
                    keep_training_booster=True)
    eng = bst.engine
    # num_iteration=-1: train() pins best_iteration, which would
    # otherwise clamp the post-update predict back to 5 iterations
    p5 = bst.predict(X[:100], num_iteration=-1)
    s5, _ = eng._stack_for_predict(0, len(eng.models))
    bst.update()                       # model grew by one iteration
    s6, _ = eng._stack_for_predict(0, len(eng.models))
    assert s6 is not s5
    p6 = bst.predict(X[:100], num_iteration=-1)
    assert not np.array_equal(p5, p6)  # new tree actually contributes


def test_dart_rescale_invalidates_stack_cache():
    """DART mutates stored trees in place (shrink) without changing the
    model count — the version bump must drop cached stacks."""
    X, y = _data(n=600)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "regression", "boosting": "dart",
                     "num_leaves": 15, "drop_rate": 0.9, "skip_drop": 0.0,
                     "verbosity": -1}, ds, num_boost_round=3,
                    keep_training_booster=True)
    eng = bst.engine
    ver0 = eng._models_version
    bst.update()
    assert eng._models_version > ver0
    # predictions after the update must match a fresh host-side stack
    eng.config.tpu_predict_cache = False
    fresh = bst.predict(X[:100], raw_score=True)
    eng.config.tpu_predict_cache = True
    cached = bst.predict(X[:100], raw_score=True)
    assert np.array_equal(fresh, cached)


def test_bounded_compiles_across_ragged_sizes():
    """The bucketing guarantee, pinned: after warming the row buckets,
    predicts at ANY size covered by those buckets compile nothing."""
    X, y = _data(n=2000)
    bst = _train({"objective": "binary", "num_leaves": 15}, X, y)
    before = predict_program_cache_size()
    for n in (128, 256, 512, 1024, 2000):   # warm each bucket once
        bst.predict(X[:n])
    grew = predict_program_cache_size() - before
    assert grew <= 5
    with CompileWatch() as watch:
        for n in (1, 3, 60, 130, 300, 700, 1025, 1999):
            bst.predict(X[:n])
    assert watch.compiles == 0, watch.events
    assert predict_program_cache_size() - before == grew


def test_early_stop_slices_share_bucketed_shapes():
    """num_iteration slices pad the stack to power-of-two tree counts:
    distinct slice lengths in the same bucket reuse one compiled
    traversal (early-stop serving must not compile per slice)."""
    X, y = _data(n=500)
    bst = _train({"objective": "binary", "num_leaves": 15}, X, y,
                 rounds=16)
    eng = bst.engine
    s5, _ = eng._stack_for_predict(0, 5)
    s7, _ = eng._stack_for_predict(0, 7)
    assert all(s5[k].shape == s7[k].shape for k in s5)   # same bucket (8)
    bst.predict(X[:256], num_iteration=5)                # warm bucket
    with CompileWatch() as watch:
        bst.predict(X[:256], num_iteration=6)
        bst.predict(X[:256], num_iteration=7)
        bst.predict(X[:200], num_iteration=8)            # same buckets
    assert watch.compiles == 0, watch.events


# ---------------------------------------------------------------------------
# 4. Booster host-model cache (pred_contrib / pred_early_stop serving)
# ---------------------------------------------------------------------------

def test_host_model_cached_across_pred_contrib_calls(monkeypatch):
    from lightgbm_tpu.io.model_text import HostModel
    X, y = _data(n=400)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1}, ds, num_boost_round=4,
                    keep_training_booster=True)
    builds = []
    orig = HostModel.from_engine

    def counting(*a, **k):
        builds.append(1)
        return orig(*a, **k)

    monkeypatch.setattr(HostModel, "from_engine", staticmethod(counting))
    c1 = bst.predict(X[:50], pred_contrib=True)
    c2 = bst.predict(X[:50], pred_contrib=True)
    assert len(builds) == 1            # second call reused the cache
    assert np.array_equal(c1, c2)
    bst.update()                       # growth invalidates
    bst.predict(X[:50], pred_contrib=True)
    assert len(builds) == 2
