"""Native binning hot paths: bit-exact parity with the Python fallback.

The C ports (native/binning.cpp: greedy_find_bounds, bin_numeric_column)
must produce IDENTICAL bounds and bin ids to io/binning.py's Python
implementations — bins shifting by one would silently change every
model. Parity is checked on adversarial inputs: NaNs, exact zeros,
heavy repeated values, f32/f64, strided column views.
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.io import binning
from lightgbm_tpu.io.binning import (BinMapper,
                                     _greedy_find_distinct_bounds,
                                     _distinct_with_counts)

pytestmark = pytest.mark.skipif(binning._native() is None,
                                reason="no native toolchain")


def _python_only(monkeypatch):
    monkeypatch.setattr(binning, "_native", lambda: None)


def _sample_sets():
    rng = np.random.default_rng(0)
    out = []
    # continuous: ~all distinct
    out.append(rng.normal(size=150_000))
    # heavy masses: a few values dominate
    v = rng.normal(size=100_000)
    v[rng.random(100_000) < 0.4] = 1.25
    v[rng.random(100_000) < 0.2] = -3.5
    out.append(v)
    # discrete-ish: few distinct values
    out.append(rng.integers(0, 37, size=80_000).astype(np.float64))
    # with zeros and NaNs
    v = rng.normal(size=120_000)
    v[rng.random(120_000) < 0.3] = 0.0
    v[rng.random(120_000) < 0.1] = np.nan
    out.append(v)
    return out


def test_greedy_bounds_parity(monkeypatch):
    for vals in _sample_sets():
        finite = vals[~np.isnan(vals)]
        for side in (finite[finite > 0], -finite[finite < 0]):
            dv, cnt = _distinct_with_counts(np.sort(side))
            for mb in (15, 63, 255):
                nat = _greedy_find_distinct_bounds(
                    dv, cnt, mb, len(side), 3)
                with monkeypatch.context() as m:
                    m.setattr(binning, "_native", lambda: None)
                    py = _greedy_find_distinct_bounds(
                        dv, cnt, mb, len(side), 3)
                assert nat == py, (mb, len(dv))


def test_bin_apply_parity(monkeypatch):
    rng = np.random.default_rng(1)
    for vals in _sample_sets():
        for zero_as_missing in (False, True):
            m0 = BinMapper.from_sample(
                vals[:50_000], 50_000, 255, 3, True, zero_as_missing)
            nat = m0.values_to_bins(vals)
            with monkeypatch.context() as m:
                m.setattr(binning, "_native", lambda: None)
                py = m0.values_to_bins(vals)
            np.testing.assert_array_equal(nat, py)
            # f32 input binned natively == f64 Python path (f32->f64
            # promotion is exact)
            nat32 = m0.values_to_bins(vals.astype(np.float32))
            with monkeypatch.context() as m:
                m.setattr(binning, "_native", lambda: None)
                py32 = m0.values_to_bins(
                    vals.astype(np.float32).astype(np.float64))
            np.testing.assert_array_equal(nat32, py32)


def test_strided_column_views():
    rng = np.random.default_rng(2)
    X = rng.normal(size=(100_000, 4)).astype(np.float32)
    X[rng.random(X.shape) < 0.05] = np.nan
    m0 = BinMapper.from_sample(
        X[:50_000, 1].astype(np.float64), 50_000, 255, 3, True, False)
    col = X[:, 1]                      # strided view, stride 4
    assert col.strides[0] == 16
    np.testing.assert_array_equal(
        m0.values_to_bins(col),
        m0.values_to_bins(np.ascontiguousarray(col)))


def test_dataset_fast_path_matches_f64(monkeypatch):
    rng = np.random.default_rng(3)
    X32 = rng.normal(size=(200_000, 6)).astype(np.float32)
    X32[rng.random(X32.shape) < 0.05] = np.nan
    y = (np.nansum(X32[:, :2], axis=1) > 0).astype(np.float64)
    ds_fast = lgb.Dataset(X32, label=y, free_raw_data=False)
    ds_fast.construct()
    with monkeypatch.context() as m:
        m.setattr(binning, "_native", lambda: None)
        ds_py = lgb.Dataset(X32.astype(np.float64), label=y,
                            free_raw_data=False)
        ds_py.construct()
    np.testing.assert_array_equal(ds_fast.binned, ds_py.binned)
    for a, b in zip(ds_fast.bin_mappers, ds_py.bin_mappers):
        np.testing.assert_array_equal(a.bin_upper_bound,
                                      b.bin_upper_bound)
        assert a.num_bin == b.num_bin
        assert a.missing_type == b.missing_type
        assert a.default_bin == b.default_bin


def test_training_unchanged_by_native(monkeypatch):
    rng = np.random.default_rng(4)
    X = rng.normal(size=(80_000, 5)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2] > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 31, "verbosity": -1,
              "bin_construct_sample_cnt": 30_000}
    p_fast = lgb.train(params, lgb.Dataset(X, label=y),
                       num_boost_round=5).predict(X[:1000])
    with monkeypatch.context() as m:
        m.setattr(binning, "_native", lambda: None)
        p_py = lgb.train(params, lgb.Dataset(
            X.astype(np.float64), label=y),
            num_boost_round=5).predict(X[:1000].astype(np.float64))
    np.testing.assert_allclose(p_fast, p_py, rtol=1e-6, atol=1e-7)
