"""Round-3 parameter coverage: path_smooth, extra_trees,
feature_contri, reg_sqrt, stochastic_rounding, importance type, and the
zero-silently-ignored-params contract (VERDICT r2 item 6).

Reference semantics (UNVERIFIED — empty mount): feature_histogram.hpp
(USE_SMOOTHING, USE_RAND_SEED / extra_trees, feature penalty),
regression_objective.hpp (sqrt mode), config_auto.cpp (every documented
param acts)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _data(n=3000, f=6, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = X @ rng.normal(size=f) + rng.normal(scale=0.3, size=n)
    return X, y


# ---------------------------------------------------------------------------
# path_smooth
# ---------------------------------------------------------------------------
def test_path_smooth_changes_model_and_shrinks_leaves():
    X, y = _data()
    plain = lgb.train({"objective": "regression", "num_leaves": 31,
                       "verbosity": -1}, lgb.Dataset(X, label=y),
                      num_boost_round=10)
    smooth = lgb.train({"objective": "regression", "num_leaves": 31,
                        "path_smooth": 50.0, "verbosity": -1},
                       lgb.Dataset(X, label=y), num_boost_round=10)
    p0, p1 = plain.predict(X), smooth.predict(X)
    assert not np.allclose(p0, p1)
    # smoothing pulls leaf outputs toward parents -> lower variance of
    # per-tree leaf values in the very first tree
    t0 = plain.engine.models[0]
    t1 = smooth.engine.models[0]
    n0 = int(np.asarray(t0.num_leaves))
    n1 = int(np.asarray(t1.num_leaves))
    v0 = np.asarray(t0.leaf_value)[:n0]
    v1 = np.asarray(t1.leaf_value)[:n1]
    assert np.std(v1) < np.std(v0)
    # still a sane model
    assert np.corrcoef(p1, y)[0, 1] > 0.9


def test_path_smooth_zero_is_noop():
    X, y = _data(seed=1)
    a = lgb.train({"objective": "regression", "num_leaves": 15,
                   "verbosity": -1}, lgb.Dataset(X, label=y),
                  num_boost_round=5)
    b = lgb.train({"objective": "regression", "num_leaves": 15,
                   "path_smooth": 0.0, "verbosity": -1},
                  lgb.Dataset(X, label=y), num_boost_round=5)
    np.testing.assert_array_equal(a.predict(X), b.predict(X))


# ---------------------------------------------------------------------------
# extra_trees
# ---------------------------------------------------------------------------
def test_extra_trees_randomizes_thresholds():
    X, y = _data(seed=2)
    plain = lgb.train({"objective": "regression", "num_leaves": 31,
                       "verbosity": -1}, lgb.Dataset(X, label=y),
                      num_boost_round=8)
    extra = lgb.train({"objective": "regression", "num_leaves": 31,
                       "extra_trees": True, "verbosity": -1},
                      lgb.Dataset(X, label=y), num_boost_round=8)
    assert not np.allclose(plain.predict(X), extra.predict(X))
    # random single thresholds fit train data no better than full scans
    mse_p = np.mean((plain.predict(X) - y) ** 2)
    mse_e = np.mean((extra.predict(X) - y) ** 2)
    assert mse_e >= mse_p * 0.99
    # extra_seed changes the drawn thresholds
    extra2 = lgb.train({"objective": "regression", "num_leaves": 31,
                        "extra_trees": True, "extra_seed": 99,
                        "verbosity": -1},
                       lgb.Dataset(X, label=y), num_boost_round=8)
    assert not np.allclose(extra.predict(X), extra2.predict(X))


def test_extra_trees_same_seed_deterministic():
    X, y = _data(seed=3)
    ps = {"objective": "regression", "num_leaves": 15,
          "extra_trees": True, "verbosity": -1}
    a = lgb.train(ps, lgb.Dataset(X, label=y), num_boost_round=5)
    b = lgb.train(ps, lgb.Dataset(X, label=y), num_boost_round=5)
    np.testing.assert_array_equal(a.predict(X), b.predict(X))


# ---------------------------------------------------------------------------
# feature_contri
# ---------------------------------------------------------------------------
def test_feature_contri_suppresses_feature():
    rng = np.random.default_rng(4)
    X = rng.normal(size=(3000, 3))
    # f0 dominates; near-zero contri should demote it
    y = 3.0 * X[:, 0] + 0.5 * X[:, 1] + rng.normal(scale=0.1, size=3000)
    plain = lgb.train({"objective": "regression", "num_leaves": 15,
                       "verbosity": -1}, lgb.Dataset(X, label=y),
                      num_boost_round=5)
    demoted = lgb.train({"objective": "regression", "num_leaves": 15,
                         "feature_contri": [1e-6, 1.0, 1.0],
                         "verbosity": -1},
                        lgb.Dataset(X, label=y), num_boost_round=5)

    def root_feature(bst):
        t = bst.engine.models[0]
        return bst.engine.train_set.used_features[int(t.split_feature[0])]

    assert root_feature(plain) == 0
    assert root_feature(demoted) != 0
    # all-ones contri is a no-op
    ones = lgb.train({"objective": "regression", "num_leaves": 15,
                      "feature_contri": [1.0, 1.0, 1.0],
                      "verbosity": -1},
                     lgb.Dataset(X, label=y), num_boost_round=5)
    np.testing.assert_array_equal(plain.predict(X), ones.predict(X))


# ---------------------------------------------------------------------------
# reg_sqrt
# ---------------------------------------------------------------------------
def test_reg_sqrt_roundtrip_and_transform():
    rng = np.random.default_rng(5)
    X = rng.normal(size=(3000, 4))
    y = np.exp(X[:, 0] + 0.2 * X[:, 1])      # heavy-tailed positive
    bst = lgb.train({"objective": "regression", "reg_sqrt": True,
                     "num_leaves": 31, "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=30)
    pred = bst.predict(X)
    # predictions come back in label space (sign(s) * s^2)
    assert np.corrcoef(pred, y)[0, 1] > 0.9
    raw = bst.predict(X, raw_score=True)
    np.testing.assert_allclose(pred, np.sign(raw) * raw * raw,
                               rtol=1e-6)
    # model text round-trip keeps the sqrt transform
    s = bst.model_to_string()
    assert "objective=regression sqrt" in s
    re_bst = lgb.Booster(model_str=s)
    np.testing.assert_allclose(re_bst.predict(X), pred, rtol=1e-5,
                               atol=1e-5)


# ---------------------------------------------------------------------------
# stochastic_rounding
# ---------------------------------------------------------------------------
def test_stochastic_rounding_off_is_deterministic_rounding():
    X, y = _data(seed=6)
    ps = {"objective": "regression", "num_leaves": 15,
          "use_quantized_grad": True, "verbosity": -1,
          "stochastic_rounding": False}
    a = lgb.train(ps, lgb.Dataset(X, label=y), num_boost_round=5)
    b = lgb.train({**ps, "seed": 123}, lgb.Dataset(X, label=y),
                  num_boost_round=5)
    # without stochastic rounding the quantization ignores the RNG seed
    np.testing.assert_array_equal(a.predict(X), b.predict(X))
    on = lgb.train({**ps, "stochastic_rounding": True},
                   lgb.Dataset(X, label=y), num_boost_round=5)
    assert not np.allclose(a.predict(X), on.predict(X))


# ---------------------------------------------------------------------------
# saved_feature_importance_type
# ---------------------------------------------------------------------------
def test_saved_importance_type_gain():
    X, y = _data(seed=7)
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "saved_feature_importance_type": 1,
                     "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=5)
    txt = bst.model_to_string()
    sec = txt.split("feature_importances:\n")[1].split("\n\n")[0]
    vals = [float(line.split("=")[1]) for line in sec.strip().splitlines()]
    # gain importances are non-integer in general
    assert any(abs(v - round(v)) > 1e-9 for v in vals), vals


# ---------------------------------------------------------------------------
# unimplemented params warn (never silently ignored)
# ---------------------------------------------------------------------------
def test_tpu_debug_catches_nan_custom_objective():
    """VERDICT r2 item 10: a NaN-producing custom objective must raise
    an actionable error with iteration context instead of silently
    training NaN trees."""
    X, y = _data(seed=8)

    def bad_fobj(preds, ds):
        g = preds - ds.get_label()
        g = np.where(np.arange(len(g)) == 7, np.nan, g)
        return g, np.ones_like(g)

    with pytest.raises(lgb.LightGBMError, match="tpu_debug at iteration"):
        lgb.train({"objective": "custom", "tpu_debug": True,
                   "num_leaves": 15, "verbosity": -1},
                  lgb.Dataset(X, label=y), num_boost_round=3,
                  fobj=bad_fobj)


def test_tpu_debug_catches_nan_labels_on_device():
    """Built-in objective fed poisoned labels: the checkify pass flags
    non-finite gradients on-device."""
    X, y = _data(seed=9)
    y = y.copy()
    y[3] = np.nan
    with pytest.raises(lgb.LightGBMError, match="non-finite"):
        lgb.train({"objective": "regression", "tpu_debug": True,
                   "num_leaves": 15, "verbosity": -1,
                   "boost_from_average": False},
                  lgb.Dataset(X, label=y), num_boost_round=3)


def test_tpu_debug_catches_nan_hessian_custom_objective():
    """The custom-fobj host-side validation must flag non-finite
    HESSIANS with the documented diagnostic too (a silent NaN hessian
    would corrupt every leaf output downstream)."""
    X, y = _data(seed=21)

    def bad_fobj(preds, ds):
        g = preds - ds.get_label()
        h = np.ones_like(g)
        h[5] = np.inf
        return g, h

    with pytest.raises(lgb.LightGBMError,
                       match="non-finite hessian"):
        lgb.train({"objective": "custom", "tpu_debug": True,
                   "num_leaves": 15, "verbosity": -1},
                  lgb.Dataset(X, label=y), num_boost_round=3,
                  fobj=bad_fobj)


def test_tpu_debug_catches_out_of_range_init_score():
    """An out-of-range (non-finite) init_score poisons the model scores
    before the first gradient; the checkify pass must surface the
    documented score diagnostic instead of silently training NaN
    trees."""
    X, y = _data(seed=22)
    init = np.zeros(len(y))
    init[7] = np.inf
    with pytest.raises(lgb.LightGBMError,
                       match="model scores contain"):
        lgb.train({"objective": "binary", "tpu_debug": True,
                   "num_leaves": 15, "verbosity": -1},
                  lgb.Dataset(X, label=y, init_score=init),
                  num_boost_round=3)


def test_tpu_debug_clean_run_unaffected():
    X, y = _data(seed=10)
    a = lgb.train({"objective": "regression", "num_leaves": 15,
                   "verbosity": -1}, lgb.Dataset(X, label=y),
                  num_boost_round=4)
    b = lgb.train({"objective": "regression", "tpu_debug": True,
                   "num_leaves": 15, "verbosity": -1},
                  lgb.Dataset(X, label=y), num_boost_round=4)
    np.testing.assert_array_equal(a.predict(X), b.predict(X))


def test_round3_params_compose_with_data_parallel():
    """path_smooth + extra_trees + monotone intermediate must run under
    the data-parallel learner and agree with serial training (shared
    RNG keys make extra_trees deterministic across layouts; precise
    histograms remove reduction-order noise)."""
    import jax
    if jax.device_count() < 2:
        import pytest as _pt
        _pt.skip("needs a multi-device mesh")
    rng = np.random.default_rng(15)
    X = rng.uniform(-2, 2, size=(3000, 5))
    y = 0.8 * X[:, 0] + 0.5 * X[:, 1] + rng.normal(scale=0.2, size=3000)
    # deterministic searches (path_smooth + intermediate monotone):
    # serial and data-parallel must agree pointwise under precise hist
    preds = {}
    for learner in ("serial", "data"):
        bst = lgb.train(
            {"objective": "regression", "num_leaves": 15,
             "verbosity": -1, "tree_learner": learner,
             "path_smooth": 5.0,
             "monotone_constraints": [1, 0, 0, 0, 0],
             "monotone_constraints_method": "intermediate",
             "tpu_double_precision_hist": True},
            lgb.Dataset(X, label=y), num_boost_round=8)
        preds[learner] = bst.predict(X)
    np.testing.assert_allclose(preds["serial"], preds["data"],
                               rtol=1e-4, atol=1e-4)
    # with extra_trees pointwise equality is NOT guaranteed (single
    # random thresholds make per-leaf best gains near-tied, and float
    # reduction order can flip the top_k expansion order); require
    # quality-level agreement + monotonicity on the distributed model
    mses = {}
    for learner in ("serial", "data"):
        bst = lgb.train(
            {"objective": "regression", "num_leaves": 15,
             "verbosity": -1, "tree_learner": learner,
             "path_smooth": 5.0, "extra_trees": True,
             "monotone_constraints": [1, 0, 0, 0, 0],
             "monotone_constraints_method": "intermediate",
             "tpu_double_precision_hist": True},
            lgb.Dataset(X, label=y), num_boost_round=8)
        mses[learner] = float(np.mean((bst.predict(X) - y) ** 2))
    # different tree sequences => different models; both must land in
    # the same quality ballpark (the label variance is ~0.72)
    assert abs(mses["serial"] - mses["data"]) \
        < 0.35 * max(mses.values()), mses
    assert max(mses.values()) < 0.6 * float(np.var(y)), mses
    grid = np.linspace(-2, 2, 101)
    rows = np.tile(np.zeros(5), (101, 1))
    rows[:, 0] = grid
    r = lgb.Booster(model_str=bst.model_to_string()).predict(rows)
    assert np.min(np.diff(r)) >= -1e-6


def test_sparse_predict_without_densify():
    """VERDICT r2 item 9: predict on scipy input must bin column-wise
    (engine path) / chunk rows (host-model path) and match the dense
    result exactly."""
    scipy_sparse = pytest.importorskip("scipy.sparse")
    rng = np.random.default_rng(11)
    Xd = rng.normal(size=(2000, 10))
    Xd[rng.random(Xd.shape) < 0.8] = 0.0       # sparse-ish, zeros real
    y = (Xd[:, 0] + Xd[:, 1] > 0).astype(float)
    Xs = scipy_sparse.csr_matrix(Xd)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1}, lgb.Dataset(Xs, label=y),
                    num_boost_round=5)
    p_dense = bst.predict(Xd)
    p_sparse = bst.predict(Xs)                 # engine path
    np.testing.assert_allclose(p_sparse, p_dense, rtol=1e-7)
    # host-model path (loaded booster) chunks sparse rows
    hm = lgb.Booster(model_str=bst.model_to_string())
    np.testing.assert_allclose(hm.predict(Xs), hm.predict(Xd),
                               rtol=1e-7)


def test_forcedbins_boundaries_respected(tmp_path):
    """forcedbins_filename (DatasetLoader predefined-bin path): the
    listed upper bounds must appear verbatim in the feature's bin
    boundaries, so split thresholds can land exactly on them."""
    import json
    rng = np.random.default_rng(12)
    X = rng.uniform(0, 1, size=(3000, 3))
    y = (X[:, 0] > 0.337).astype(float)
    fb = str(tmp_path / "forced.json")
    with open(fb, "w") as f:
        json.dump([{"feature": 0, "bin_upper_bound": [0.337, 0.8]}], f)
    ds = lgb.Dataset(X, label=y,
                     params={"forcedbins_filename": fb, "max_bin": 16})
    ds.construct()
    ub = ds.bin_mappers[0].bin_upper_bound
    assert 0.337 in ub and 0.8 in ub, ub
    assert ds.bin_mappers[0].num_bin <= 16
    # a model trained on this data can realize the exact threshold
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1, "forcedbins_filename": fb,
                     "max_bin": 16}, ds, num_boost_round=3)
    thresholds = []
    for info in bst.dump_model()["tree_info"]:
        def walk(nd):
            if "threshold" in nd and nd["threshold"] is not None:
                thresholds.append(nd["threshold"])
            for c in ("left_child", "right_child"):
                if isinstance(nd.get(c), dict):
                    walk(nd[c])
        walk(info["tree_structure"])
    assert any(abs(t - 0.337) < 1e-12 for t in thresholds), thresholds


def test_forcedsplits_structure_respected(tmp_path):
    """forcedsplits_filename (ForceSplits): the JSON split tree must
    form the top of EVERY tree — root on f1 at 0.25, its left child on
    f2 at -0.5 — regardless of what free search would pick."""
    import json
    rng = np.random.default_rng(13)
    X = rng.uniform(-1, 1, size=(4000, 4))
    # f0 dominates, so free search would never pick f1 at the root
    y = 3.0 * X[:, 0] + 0.2 * X[:, 1] + rng.normal(scale=0.1, size=4000)
    fs = str(tmp_path / "forced.json")
    with open(fs, "w") as f:
        json.dump({"feature": 1, "threshold": 0.25,
                   "left": {"feature": 2, "threshold": -0.5}}, f)
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "forcedsplits_filename": fs, "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=4)
    used = bst.engine.train_set.used_features
    for t in bst.engine.models:
        sf = np.asarray(t.split_feature)
        assert used[int(sf[0])] == 1, "root split must be forced to f1"
        # node 1 is the left child's forced split (created round 2)
        assert used[int(sf[1])] == 2
    # model trains sanely despite the forced top
    pred = bst.predict(X)
    assert np.corrcoef(pred, y)[0, 1] > 0.8
    # thresholds land at the bin boundary containing the forced value
    info = bst.dump_model()["tree_info"][0]["tree_structure"]
    assert abs(info["threshold"] - 0.25) < 0.05
    # plain training (no forced file) picks f0 at the root instead
    plain = lgb.train({"objective": "regression", "num_leaves": 15,
                       "verbosity": -1}, lgb.Dataset(X, label=y),
                      num_boost_round=1)
    t0 = plain.engine.models[0]
    assert used[int(np.asarray(t0.split_feature)[0])] == 0


def test_forcedsplits_siblings_apply_together(tmp_path):
    """Round 4: independent forced entries (siblings) land in the SAME
    leaf-batch round — a root + both children table fills nodes 0..2
    of every tree with the forced structure (the old one-entry-per-
    round path consumed k rounds; now ~depth(table))."""
    import json
    rng = np.random.default_rng(21)
    X = rng.uniform(-1, 1, size=(4000, 5))
    y = 3.0 * X[:, 0] + rng.normal(scale=0.1, size=4000)
    fs = str(tmp_path / "forced.json")
    with open(fs, "w") as f:
        json.dump({"feature": 1, "threshold": 0.0,
                   "left": {"feature": 2, "threshold": 0.1},
                   "right": {"feature": 3, "threshold": -0.1}}, f)
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "forcedsplits_filename": fs, "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=3)
    used = bst.engine.train_set.used_features
    assert bst.engine._n_forced == 3
    for t in bst.engine.models:
        sf = [used[int(f)] for f in np.asarray(t.split_feature[:3])]
        assert sf[0] == 1, sf
        # both sibling entries applied in the round after the root
        assert set(sf[1:3]) == {2, 3}, sf
    assert np.corrcoef(bst.predict(X), y)[0, 1] > 0.8


def test_forcedsplits_categorical(tmp_path):
    """Round 4: forced CATEGORICAL entries — "threshold" lists the
    category values that go left; the node must appear as a
    categorical split at the top of every tree."""
    import json
    rng = np.random.default_rng(22)
    n = 4000
    X = rng.uniform(-1, 1, size=(n, 4))
    c = rng.integers(0, 8, size=n)
    X[:, 3] = c
    y = (2.0 * X[:, 0] + np.where(np.isin(c, [2, 5]), 1.5, 0.0)
         + rng.normal(scale=0.1, size=n))
    fs = str(tmp_path / "forced.json")
    with open(fs, "w") as f:
        json.dump({"feature": 3, "threshold": [2, 5]}, f)
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "forcedsplits_filename": fs, "verbosity": -1},
                    lgb.Dataset(X, label=y, categorical_feature=[3]),
                    num_boost_round=3)
    assert bst.engine._n_forced == 1
    used = bst.engine.train_set.used_features
    for t in bst.engine.models:
        assert used[int(t.split_feature[0])] == 3
        assert t.is_categorical is not None and t.is_categorical[0]
    # categories 2 and 5 route together at the root
    pred = bst.predict(X)
    assert np.corrcoef(pred, y)[0, 1] > 0.8
    # an unseen-category-only forced split is skipped gracefully
    fs2 = str(tmp_path / "forced2.json")
    with open(fs2, "w") as f:
        json.dump({"feature": 3, "threshold": [99]}, f)
    b2 = lgb.train({"objective": "regression", "num_leaves": 7,
                    "forcedsplits_filename": fs2, "verbosity": -1},
                   lgb.Dataset(X, label=y, categorical_feature=[3]),
                   num_boost_round=2)
    assert b2.engine._n_forced == 0


def test_forcedsplits_inapplicable_entry_resumes_free_growth(tmp_path):
    """A forced entry skipped at RUNTIME (threshold above the feature's
    range -> an empty child) must not halt growth: free search resumes
    and the trees still learn (round-4 termination fix)."""
    import json
    rng = np.random.default_rng(23)
    X = rng.uniform(-1, 1, size=(3000, 3))
    y = 2.0 * X[:, 0] + rng.normal(scale=0.1, size=3000)
    fs = str(tmp_path / "forced.json")
    with open(fs, "w") as f:
        json.dump({"feature": 1, "threshold": 100.0,    # beyond max
                   "left": {"feature": 2, "threshold": 0.0}}, f)
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "forcedsplits_filename": fs, "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=4)
    # the skipped entry cancelled its subtree, but trees grew freely
    assert all(int(np.asarray(t.num_leaves)) > 2
               for t in bst.engine.models)
    assert np.corrcoef(bst.predict(X), y)[0, 1] > 0.9


def test_forcedsplits_unused_feature_skipped(tmp_path):
    """A forced split on a constant (dropped) feature is skipped with
    its subtree; training proceeds normally."""
    import json
    rng = np.random.default_rng(14)
    X = rng.normal(size=(1500, 3))
    X[:, 2] = 7.0                       # constant -> dropped
    y = X[:, 0] + rng.normal(scale=0.2, size=1500)
    fs = str(tmp_path / "forced.json")
    with open(fs, "w") as f:
        json.dump({"feature": 2, "threshold": 0.0}, f)
    bst = lgb.train({"objective": "regression", "num_leaves": 7,
                     "forcedsplits_filename": fs, "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=3)
    assert bst.engine._n_forced == 0
    assert np.corrcoef(bst.predict(X), y)[0, 1] > 0.8


def test_unimplemented_param_warns():
    from lightgbm_tpu.config import Config, _WARNED_PARAM_VALUES
    from lightgbm_tpu.utils import log
    _WARNED_PARAM_VALUES.discard(("parser_config_file",
                                  repr("parser.json")))
    msgs = []
    log.register_callback(msgs.append)
    try:
        Config({"objective": "binary", "verbosity": 1,
                "parser_config_file": "parser.json"})
    finally:
        log.register_callback(None)
        log.set_verbosity(-1)
    assert any("parser_config_file" in m for m in msgs), msgs


def test_cegb_lazy_differs_from_coupled():
    """cegb_penalty_feature_lazy (round 4): per-row acquisition — the
    penalty scales with the UNACQUIRED row count of the candidate
    leaf, so (a) a large lazy penalty suppresses a feature that the
    same-value COUPLED penalty (charged once per model) still buys,
    and (b) zero penalties reproduce the unpenalized model."""
    rng = np.random.default_rng(31)
    X = rng.normal(size=(3000, 5))
    y = 2.0 * X[:, 0] + 0.5 * X[:, 1] + rng.normal(scale=0.2, size=3000)
    base = {"objective": "regression", "num_leaves": 15,
            "verbosity": -1}

    def f0_splits(b):
        used = b.engine.train_set.used_features
        u0 = used.index(0)
        return sum(int(np.sum(np.asarray(
            t.split_feature[:t.num_nodes]) == u0))
            for t in b.engine.models)

    b0 = lgb.train(base, lgb.Dataset(X, label=y), num_boost_round=6)
    bl = lgb.train({**base,
                    "cegb_penalty_feature_lazy": [50.0, 0, 0, 0, 0]},
                   lgb.Dataset(X, label=y), num_boost_round=6)
    bc = lgb.train({**base,
                    "cegb_penalty_feature_coupled": [50.0, 0, 0, 0, 0]},
                   lgb.Dataset(X, label=y), num_boost_round=6)
    assert f0_splits(b0) > 0
    assert f0_splits(bl) == 0            # per-row cost prices f0 out
    assert f0_splits(bc) > 0             # one-off cost does not
    # zero lazy penalties == baseline, bit for bit
    bz = lgb.train({**base,
                    "cegb_penalty_feature_lazy": [0, 0, 0, 0, 0]},
                   lgb.Dataset(X, label=y), num_boost_round=6)
    np.testing.assert_allclose(bz.predict(X[:200]), b0.predict(X[:200]),
                               rtol=1e-7)


def test_cegb_lazy_acquisition_discounts_later_trees():
    """Once rows acquire a feature (their path used it), later splits
    on it cost nothing for those rows: with a moderate lazy penalty
    the feature still enters the model (unlike the prohibitive case),
    and the fit stays sane."""
    rng = np.random.default_rng(32)
    X = rng.normal(size=(4000, 4))
    y = 3.0 * X[:, 0] + rng.normal(scale=0.2, size=4000)
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "verbosity": -1,
                     "cegb_penalty_feature_lazy": [0.5, 0, 0, 0]},
                    lgb.Dataset(X, label=y), num_boost_round=8)
    used = bst.engine.train_set.used_features
    u0 = used.index(0)
    per_tree = [int(np.sum(np.asarray(
        t.split_feature[:t.num_nodes]) == u0))
        for t in bst.engine.models]
    assert sum(per_tree) > 0             # moderate cost is payable
    assert np.corrcoef(bst.predict(X), y)[0, 1] > 0.9


def _f0_splits_per_tree(bst):
    used = bst.engine.train_set.used_features
    u0 = used.index(0)
    return [int(np.sum(np.asarray(
        t.split_feature[:t.num_nodes]) == u0))
        for t in bst.engine.models]


def test_cegb_lazy_within_tree_reuse_free():
    """Splits on a feature DEEPER in the same tree are penalty-free for
    rows that already passed a split on it (the reference marks
    feature-used-in-data on split application, mid-tree). The target
    is a 4-step staircase in x0 alone. Measured unpenalized gains:
    root 92k over 4000 rows (23/row), deeper x0 splits 22, 10.5 and
    0.08 per row. At penalty 15/row the root still pays; a
    DOUBLE-CHARGED child bill (rows re-billed at each deeper x0
    split) prices the 160-row (1682 < 15*160) and 3280-row
    (252 < 15*3280) splits out, capping x0 splits at 2 — correct
    in-tree acquisition keeps all 4."""
    rng = np.random.default_rng(33)
    n = 4000
    X = rng.normal(size=(n, 4))
    y = np.floor((X[:, 0] - X[:, 0].min()) * 1.2).clip(0, 3) * 10.0
    y += rng.normal(scale=0.1, size=n)
    bst = lgb.train({"objective": "regression", "num_leaves": 8,
                     "verbosity": -1, "learning_rate": 1.0,
                     "cegb_penalty_feature_lazy": [15.0, 0, 0, 0]},
                    lgb.Dataset(X, label=y), num_boost_round=1)
    per_tree = _f0_splits_per_tree(bst)
    assert per_tree[0] >= 3, per_tree


def test_cegb_lazy_counts_only_sampled_rows():
    """The lazy penalty bills rows of the SAMPLED partition only
    (goss.hpp/bagging.hpp partitions hold just the sampled indices).
    Measured root gains here: 14802 over 6000 rows full (2.47/row),
    ~7571 over ~3034 in-bag rows at bagging_fraction=0.5 (2.50/row).
    At penalty 2.0: billing in-bag rows costs ~6068 < 7571 (split
    pays); billing ALL 6000 rows costs 12000 > 7571 (split priced
    out). So the bagged run splits x0 iff out-of-bag rows are
    excluded from the bill."""
    rng = np.random.default_rng(34)
    n = 6000
    X = rng.normal(size=(n, 4))
    y = 2.0 * X[:, 0] + rng.normal(scale=0.2, size=n)
    pen = [2.0, 0, 0, 0]
    full = lgb.train({"objective": "regression", "num_leaves": 4,
                      "verbosity": -1,
                      "cegb_penalty_feature_lazy": pen},
                     lgb.Dataset(X, label=y), num_boost_round=1)
    assert sum(_f0_splits_per_tree(full)) > 0     # sanity: affordable
    bag = lgb.train({"objective": "regression", "num_leaves": 4,
                     "verbosity": -1, "bagging_fraction": 0.5,
                     "bagging_freq": 1, "bagging_seed": 7,
                     "cegb_penalty_feature_lazy": pen},
                    lgb.Dataset(X, label=y), num_boost_round=1)
    assert sum(_f0_splits_per_tree(bag)) > 0, \
        "lazy penalty billed out-of-bag rows"
