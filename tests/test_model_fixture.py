"""Hand-authored reference-format model fixture (VERDICT r2 weak #8).

The round-trip tests elsewhere only prove writer==reader; this fixture
pins the LightGBM v4 text FORMAT itself, independent of the writer's
own conventions: a numerical split with NaN default-left
(decision_type = 2|8), a categorical bitset split (decision_type = 1,
cat_boundaries/cat_threshold indexing), and a linear-leaf tree
(is_linear, leaf_const/num_features/leaf_features/leaf_coeff flattened
layout) — predictions asserted against hand-computed expectations."""
import numpy as np

import lightgbm_tpu as lgb

FIXTURE = """tree
version=v4
num_class=1
num_tree_per_iteration=1
label_index=0
max_feature_idx=1
objective=regression
feature_names=f0 f1
feature_infos=[-5:5] 0:1:2:3:5
tree_sizes=400 450 470

Tree=0
num_leaves=2
num_cat=0
split_feature=0
split_gain=1
threshold=0.5
decision_type=10
left_child=-1
right_child=-2
leaf_value=1.5 -2.5
leaf_weight=10 10
leaf_count=10 10
internal_value=0
internal_weight=20
internal_count=20
is_linear=0
shrinkage=1

Tree=1
num_leaves=2
num_cat=1
split_feature=1
split_gain=1
threshold=0
decision_type=1
left_child=-1
right_child=-2
leaf_value=10 -20
leaf_weight=10 10
leaf_count=10 10
internal_value=0
internal_weight=20
internal_count=20
cat_boundaries=0 1
cat_threshold=5
is_linear=0
shrinkage=1

Tree=2
num_leaves=2
num_cat=0
split_feature=0
split_gain=1
threshold=0.0
decision_type=0
left_child=-1
right_child=-2
leaf_value=0 0
leaf_weight=10 10
leaf_count=10 10
internal_value=0
internal_weight=20
internal_count=20
is_linear=1
leaf_const=1.0 -1.0
num_features=1 0
leaf_features=0
leaf_coeff=2.0
shrinkage=1

end of trees

feature_importances:
f0=2
f1=1

parameters:
[objective: regression]
end of parameters

pandas_categorical:null
"""


def test_fixture_predictions_hand_computed():
    bst = lgb.Booster(model_str=FIXTURE)
    nan = float("nan")
    X = np.array([
        [0.0, 0.0],    # t0: 0<=0.5 left 1.5 | t1: cat0 in {0,2} 10
                       # | t2: left, 1+2*0=1            -> 12.5
        [1.0, 1.0],    # right -2.5 | cat1 out -20 | right -1 -> -23.5
        [nan, 2.0],    # NaN default-left 1.5 | cat2 in 10
                       # | t2 routes NaN->0 (missing_type=none) left,
                       #   but the LINEAR model sees the raw NaN ->
                       #   nan_found -> constant leaf_value 0    -> 11.5
        [2.0, 3.0],    # right -2.5 | cat3 out -20 | right -1  -> -23.5
        [0.6, nan],    # right -2.5 | NaN cat routes right -20
                       # | right -1                            -> -23.5
        [-1.0, 5.0],   # left 1.5 | cat5 out -20
                       # | left, 1+2*(-1)=-1                   -> -19.5
    ])
    expected = np.array([12.5, -23.5, 11.5, -23.5, -23.5, -19.5])
    pred = bst.predict(X)
    np.testing.assert_allclose(pred, expected, rtol=0, atol=1e-9)


def test_fixture_survives_roundtrip():
    """Loading the fixture and re-saving must preserve predictions (the
    writer must not corrupt structures it did not author)."""
    bst = lgb.Booster(model_str=FIXTURE)
    re_bst = lgb.Booster(model_str=bst.model_to_string())
    X = np.array([[0.0, 0.0], [1.0, 1.0], [2.0, 3.0], [-1.0, 5.0]])
    np.testing.assert_allclose(re_bst.predict(X), bst.predict(X),
                               rtol=0, atol=1e-9)
