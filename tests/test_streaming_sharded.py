"""Streamed x sharded training (boosting/streaming.py tree_learner=
data): each rank streams only its own row shard's blocks, accumulates
its local [K, F, B, 3] level histogram, and ONE psum / psum_scatter per
tree level through the shared packed-int32 wire (learner/collective.py)
makes every rank grow bit-identical trees.

The acceptance invariants pinned here:
* sharded trees BIT-IDENTICAL to single-shard streaming at 1/2/4
  shards (plain f32, quantized+packed wire, GOSS, bagging);
* exactly ONE histogram collective per tree level regardless of block
  count (the ``comm.allreduce_calls`` counter);
* bagging and GOSS train on the streaming engine, seed-reproducible,
  quality-par with the in-core path;
* ``_streaming_compatible`` accepts a config IFF StreamingGBDT's
  ``_no()`` gates do (the drift guard — PR 5 fixed two bugs from
  exactly this drift);
* a rank that would stream zero blocks fatals EARLY (mirrors
  ``_cli_file_shard``'s row-count check);
* ``tpu_streaming=auto`` routes an over-HBM mesh config onto the
  sharded streaming path.
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.utils.log import LightGBMError


def _data(n=16_000, f=10, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2]
         + rng.normal(scale=0.3, size=n) > 0).astype(np.float64)
    return X, y


BASE = {"objective": "binary", "num_leaves": 16, "max_depth": 4,
        "verbosity": -1, "min_data_in_leaf": 20,
        "tpu_streaming": "true", "tpu_stream_block_rows": 2_048}


def _train(X, y, shards, rounds=5, **extra):
    p = dict(BASE, **extra)
    if shards > 1:
        p["tree_learner"] = "data"
        p["tpu_mesh_shape"] = shards
    return lgb.train(p, lgb.Dataset(X, label=y),
                     num_boost_round=rounds)


# ---------------------------------------------------------------------------
# bit-identity across shard counts
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("extra", [
    {},                                                  # plain f32
    {"use_quantized_grad": True},                        # packed wire
    {"use_quantized_grad": True,
     "data_sample_strategy": "goss"},
    {"bagging_fraction": 0.6, "bagging_freq": 2},
], ids=["plain", "quant", "quant_goss", "bagging"])
def test_sharded_bit_identical_to_single_shard(extra):
    """1/2/4-shard streamed training must produce the same model text
    byte for byte: per-rank partial histograms are exact sums (integer
    level sums under quantization; bf16-rounded contributions with
    24-bit f32 headroom otherwise), so the per-level reduction is
    association-free, and the bagging/GOSS row hash keys on GLOBAL row
    indices."""
    X, y = _data()
    texts = {s: _train(X, y, s, **extra).model_to_string()
             for s in (1, 2, 4)}
    assert texts[1] == texts[2]
    assert texts[1] == texts[4]


def test_sharded_scatter_and_psum_wires_agree():
    """tpu_hist_reduce=scatter (psum_scatter + best-split election)
    and =psum are two wires for the same reduction — identical trees,
    both bit-equal to the single-shard run."""
    X, y = _data(seed=2)
    ref = _train(X, y, 1, use_quantized_grad=True).model_to_string()
    for wire in ("scatter", "psum"):
        t = _train(X, y, 2, use_quantized_grad=True,
                   tpu_hist_reduce=wire).model_to_string()
        assert t == ref, wire


# ---------------------------------------------------------------------------
# one collective per level, regardless of block count
# ---------------------------------------------------------------------------
def test_one_allreduce_per_level_any_block_count():
    """The acceptance pin: the number of histogram collectives equals
    the number of tree LEVELS — never scaling with how many blocks the
    rows were cut into (the accumulate-then-reduce design)."""
    X, y = _data(n=12_000)
    engines = {}
    for blk in (2_048, 16_384):
        bst = _train(X, y, 2, rounds=4, tpu_stream_block_rows=blk)
        engines[blk] = bst.engine.comm_stats
    a, b = engines[2_048], engines[16_384]
    # more blocks were scanned at the small block size...
    assert a["blocks_scanned"] > b["blocks_scanned"]
    # ...but the collective count is pinned to the level count
    assert a["allreduce_calls"] == a["levels"]
    assert b["allreduce_calls"] == b["levels"]
    assert a["allreduce_calls"] == b["allreduce_calls"]
    assert a["allreduce_bytes"] > 0


def test_comm_obs_counters_registered():
    """stream.blocks_scanned / comm.allreduce_* land in the obs
    registry (docs/observability.md catalogue) when metrics are on."""
    from lightgbm_tpu import obs
    obs.reset()
    obs.enable(metrics=True)
    try:
        X, y = _data(n=8_000)
        bst = _train(X, y, 2, rounds=3)
        snap = obs.snapshot()
        names = {m["name"] for m in snap["metrics"]}
        assert "stream.blocks_scanned" in names
        assert "comm.allreduce_calls" in names
        assert "comm.allreduce_bytes" in names
        assert "comm.allreduce_ms" in names
        got = {m["name"]: m for m in snap["metrics"]
               if not m.get("labels")}
        cs = bst.engine.comm_stats
        assert got["comm.allreduce_calls"]["value"] == \
            cs["allreduce_calls"]
        assert got["comm.allreduce_bytes"]["value"] == \
            cs["allreduce_bytes"]
    finally:
        obs.disable()
        obs.reset()


# ---------------------------------------------------------------------------
# bagging / GOSS on the streaming engine
# ---------------------------------------------------------------------------
def test_streaming_bagging_seeded_and_quality_par():
    X, y = _data(seed=7)
    kw = dict(bagging_fraction=0.6, bagging_freq=2, rounds=10)
    t1 = _train(X, y, 1, bagging_seed=3, **kw).model_to_string()
    t2 = _train(X, y, 1, bagging_seed=3, **kw).model_to_string()
    t3 = _train(X, y, 1, bagging_seed=9, **kw).model_to_string()
    assert t1 == t2            # same seed reproduces exactly
    assert t1 != t3            # different seed actually re-draws
    # bagging actually drops rows: trees differ from the full-data run
    assert t1 != _train(X, y, 1, rounds=10).model_to_string()
    # quality parity vs the in-core engine's bagging at equal rounds
    bs = _train(X, y, 1, bagging_seed=3, **kw)
    resident = lgb.train(
        dict(BASE, tpu_streaming="false", bagging_fraction=0.6,
             bagging_freq=2, bagging_seed=3),
        lgb.Dataset(X, label=y), num_boost_round=10)
    acc_s = np.mean((bs.predict(X) > 0.5) == y)
    acc_r = np.mean((resident.predict(X) > 0.5) == y)
    assert abs(acc_s - acc_r) < 0.02


def test_streaming_goss_quality_par_and_block_invariant():
    """GOSS on the streaming engine: the global bucketed |g*h|
    threshold keeps quality par with the in-core exact top-k, and the
    hash-keyed sample is invariant to the block cut."""
    X, y = _data(seed=11)
    g = dict(data_sample_strategy="goss", rounds=10)
    bs = _train(X, y, 1, **g)
    resident = lgb.train(
        dict(BASE, tpu_streaming="false", data_sample_strategy="goss"),
        lgb.Dataset(X, label=y), num_boost_round=10)
    acc_s = np.mean((bs.predict(X) > 0.5) == y)
    acc_r = np.mean((resident.predict(X) > 0.5) == y)
    assert acc_s > 0.8
    assert abs(acc_s - acc_r) < 0.02
    # block-cut invariance (the same rows keep the same draws)
    ta = _train(X, y, 1, tpu_stream_block_rows=30_000,
                **g).model_to_string()
    assert ta == bs.model_to_string()


# ---------------------------------------------------------------------------
# drift guard: _streaming_compatible <=> StreamingGBDT._no() gates
# ---------------------------------------------------------------------------
_GATE_SWEEP = [
    ({}, True),
    ({"tree_learner": "data"}, True),
    ({"data_sample_strategy": "goss"}, True),
    ({"bagging_fraction": 0.5, "bagging_freq": 1}, True),
    ({"pos_bagging_fraction": 0.5, "neg_bagging_fraction": 0.8,
      "bagging_freq": 1}, True),
    ({"use_quantized_grad": True}, True),
    ({"extra_trees": True}, True),
    ({"feature_fraction": 0.7}, True),
    ({"objective": "regression"}, True),
    ({"tree_learner": "voting"}, False),
    ({"tree_learner": "feature"}, False),
    ({"objective": "multiclass", "num_class": 3}, False),
    ({"objective": "lambdarank"}, False),
    ({"boosting": "dart"}, False),
    ({"linear_tree": True}, False),
    ({"monotone_constraints": [1, 0, 0, 0]}, False),
    ({"interaction_constraints": [[0, 1], [2, 3]]}, False),
    ({"cegb_tradeoff": 2.0}, False),
    ({"cegb_penalty_split": 0.5}, False),
    # int16 leaf-id cap: the resident engine trains this, streaming
    # fatals — auto mode must keep it resident
    ({"num_leaves": 40_000}, False),
]


@pytest.mark.parametrize("tweak,compat", _GATE_SWEEP,
                         ids=[str(sorted(t)) for t, _ in _GATE_SWEEP])
def test_streaming_gate_drift_guard(tweak, compat):
    """_streaming_compatible(cfg) is True IFF StreamingGBDT.__init__
    accepts cfg (numerical features; dataset-level gates excluded by
    construction). Lifting or adding a gate must update BOTH sides or
    this sweep goes red — the drift that produced two PR-5 bugs.
    Seeds ROADMAP item 4's capability table."""
    from lightgbm_tpu.boosting import _streaming_compatible
    from lightgbm_tpu.boosting.streaming import StreamingGBDT
    from lightgbm_tpu.config import Config
    X, y = _data(n=640, f=4)
    params = {"objective": "binary", "num_leaves": 8, "verbosity": -1,
              "tpu_stream_block_rows": 64}
    params.update(tweak)
    cfg = Config(params)
    assert _streaming_compatible(cfg) == compat, tweak
    if "lambdarank" in str(tweak):
        y = np.arange(len(y)) % 3  # graded relevance for the objective
    ds = lgb.Dataset(X, label=y,
                     group=[len(y)] if "lambdarank" in str(tweak)
                     else None)
    if compat:
        eng = StreamingGBDT(cfg, ds)     # must construct, not fatal
        assert eng.num_features == 4
    else:
        with pytest.raises(LightGBMError):
            StreamingGBDT(cfg, ds)


def test_sharded_zero_block_rank_fatals_early():
    """n_rows < shards would hand some rank zero blocks and deadlock
    the per-level collective — construction must fatal with a clear
    message instead (mirrors _cli_file_shard's early fatal)."""
    from lightgbm_tpu.boosting.streaming import StreamingGBDT
    from lightgbm_tpu.config import Config
    X, y = _data(n=5, f=3)
    cfg = Config({"objective": "binary", "num_leaves": 4,
                  "verbosity": -1, "tree_learner": "data",
                  "min_data_in_leaf": 1})
    with pytest.raises(LightGBMError, match="zero rows"):
        StreamingGBDT(cfg, lgb.Dataset(X, label=y))


# ---------------------------------------------------------------------------
# auto routing: over-HBM mesh configs land on the sharded streamed path
# ---------------------------------------------------------------------------
def test_auto_routes_oversize_mesh_config_to_sharded_streaming(
        monkeypatch):
    """tpu_streaming=auto + tree_learner=data + a binned matrix whose
    PER-RANK shard exceeds the HBM budget -> StreamingGBDT with R > 1
    (the ROADMAP item 1 composition); a small per-rank shard keeps the
    resident sharded engine."""
    import lightgbm_tpu.utils.hbm as hbm
    from lightgbm_tpu.boosting.streaming import StreamingGBDT
    X, y = _data(n=8_000, f=6)
    est = hbm.binned_device_bytes(8_000, 6, 1)
    # per-rank (2 shards) estimate still over 60% of the "HBM" limit
    monkeypatch.setattr(hbm, "hbm_bytes_limit",
                        lambda: int(est / 2 / 0.61))
    p = {"objective": "binary", "num_leaves": 8, "verbosity": -1,
         "tree_learner": "data", "tpu_mesh_shape": 2,
         "tpu_stream_block_rows": 2_048, "min_data_in_leaf": 5}
    bst = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=2)
    assert isinstance(bst.engine, StreamingGBDT)
    assert bst.engine.R == 2
    # a roomy limit keeps the resident sharded engine
    monkeypatch.setattr(hbm, "hbm_bytes_limit", lambda: est * 100)
    bst2 = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=2)
    assert not isinstance(bst2.engine, StreamingGBDT)


# ---------------------------------------------------------------------------
# real multi-process gang (capability-gated like the other gangs)
# ---------------------------------------------------------------------------
def _stream_shard_fn(rank, nproc):
    """Module-level so spawned workers can unpickle it."""
    X, y = _data(n=4_000, f=6, seed=5)
    blk = len(X) // nproc
    lo = rank * blk
    hi = len(X) if rank == nproc - 1 else lo + blk
    return {"data": X[lo:hi], "label": y[lo:hi]}


def test_streaming_two_process_gang(multiprocess_collectives,
                                    tmp_path):
    """2 real processes, each streaming its own shard's blocks, one
    collective per level: the gang's model must equal the 1-process
    streamed run on the same rows (bin mappers synced from the union
    sample on both sides via train_distributed)."""
    params = {"objective": "binary", "num_leaves": 8, "max_depth": 3,
              "verbosity": -1, "min_data_in_leaf": 10,
              "tpu_streaming": "true", "tpu_stream_block_rows": 512,
              "use_quantized_grad": True}
    ref = lgb.train_distributed(params, _stream_shard_fn,
                                n_processes=1, num_boost_round=4,
                                timeout=240.0)
    gang = lgb.train_distributed(params, _stream_shard_fn,
                                 n_processes=2, num_boost_round=4,
                                 timeout=240.0)
    assert gang.num_trees() == 4
    assert gang.model_to_string() == ref.model_to_string()
