"""Windowed SLIs + SLO evaluation (lightgbm_tpu/obs/slo.py).

What these tests pin:

* **Quantile accuracy** — SlidingHistogram.quantile vs
  ``numpy.percentile`` on known distributions, within one value-bucket
  width (the documented estimator resolution).
* **Windowing** — observations age out of the ring: a spike older than
  the window stops moving the quantile; slot recycling keeps memory
  bounded.
* **Derived gauges + thresholds** — evaluate() publishes
  slo.predict_p99_ms / slo.error_ratio / predict.cache_hit_ratio /
  slo.queue_depth into the registry; a threshold crossing flips the
  ``slo.breached{slo=...}`` gauge and counts the TRANSITION (not every
  evaluation) in ``slo.breaches``.
* **Wiring** — the tracker feeds off the existing obs funnels
  (span/inc/observe) only when SLO is enabled, and
  ``obs.export_state`` excludes the ephemeral slo.*/heartbeat.* names
  so checkpoints never carry process-local monotonic state.
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import obs
from lightgbm_tpu.obs import slo as obs_slo

PARAMS = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
          "min_data_in_leaf": 20}


def _data(n=1200, f=8, seed=3):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    return X, y


def _bucket_width_at(bounds, v):
    """Width of the value bucket containing v (the estimator's
    documented resolution)."""
    lo = 0.0
    for hi in bounds:
        if v <= hi:
            return (hi - lo) if hi != float("inf") else float("inf")
        lo = hi
    return float("inf")


# ---------------------------------------------------------------------------
# SlidingHistogram
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("dist", ["uniform", "lognormal", "bimodal"])
def test_sliding_quantiles_match_numpy_within_bucket_width(dist):
    rng = np.random.default_rng(11)
    if dist == "uniform":
        vals = rng.uniform(0.0008, 0.3, size=8000)
    elif dist == "lognormal":
        vals = np.minimum(rng.lognormal(-5.0, 1.2, size=8000), 50.0)
    else:
        vals = np.concatenate([rng.uniform(0.001, 0.004, 6000),
                               rng.uniform(0.5, 2.0, 2000)])
    h = obs_slo.SlidingHistogram(window_s=300, slots=30)
    for v in vals:
        h.observe(float(v), now=1000.0)
    for q in (0.5, 0.9, 0.95, 0.99):
        est = h.quantile(q, now=1000.0)
        ref = float(np.percentile(vals, q * 100))
        tol = max(_bucket_width_at(h.bounds, ref),
                  _bucket_width_at(h.bounds, est))
        assert est == pytest.approx(ref, abs=tol), (dist, q)


def test_sliding_window_ages_out_old_observations():
    h = obs_slo.SlidingHistogram(window_s=60, slots=6)   # 10 s slots
    for _ in range(100):
        h.observe(10.0, now=5.0)          # slow spike at t=5
    # at t=30 the spike still dominates the window
    assert h.quantile(0.99, now=30.0) > 5.0
    for _ in range(100):
        h.observe(0.001, now=100.0)       # fast traffic at t=100
    # a window ending at t=100 starts after t=40: the spike is gone
    assert h.quantile(0.99, now=100.0) < 0.01
    assert h.count(now=100.0) == 100


def test_sliding_ring_memory_is_bounded_under_clock_advance():
    h = obs_slo.SlidingHistogram(window_s=10, slots=5)
    for t in range(0, 10_000, 7):
        h.observe(0.01, now=float(t))
    assert len(h._counts) == 5            # the ring never grows
    assert h.count(now=9997.0) <= 5 * 2   # only in-window slots counted


def test_empty_window_returns_none():
    h = obs_slo.SlidingHistogram(window_s=10, slots=5)
    assert h.quantile(0.99, now=0.0) is None
    h.observe(1.0, now=0.0)
    assert h.quantile(0.99, now=1000.0) is None   # aged out


def test_sliding_counter_window_total():
    c = obs_slo.SlidingCounter(window_s=60, slots=6)
    c.inc(5, now=5.0)
    c.inc(2, now=55.0)
    assert c.total(now=55.0) == 7.0
    assert c.total(now=100.0) == 2.0      # the t=5 slot aged out
    assert c.total(now=500.0) == 0.0


# ---------------------------------------------------------------------------
# SloTracker: derived gauges + threshold evaluation
# ---------------------------------------------------------------------------
def test_tracker_derives_gauges_and_ratios():
    t = obs_slo.SloTracker(window_s=300)
    for v in (0.002, 0.004, 0.010):
        t.feed_hist("predict/call", v, now=10.0)
    t.feed_hist("train/round", 1.5, now=10.0)
    for _ in range(10):
        t.feed_count("predict.requests", now=10.0)
    t.feed_count("predict.errors", now=10.0)
    t.feed_count("predict.stack_cache_hits", 3, now=10.0)
    t.feed_count("predict.stack_cache_misses", 1, now=10.0)
    slis = t.evaluate(now=10.0)
    assert slis["slo.error_ratio"] == pytest.approx(0.1)
    assert slis["predict.cache_hit_ratio"] == pytest.approx(0.75)
    assert 2.0 <= slis["slo.predict_p99_ms"] <= 25.0
    assert 1.0 <= slis["slo.round_p99_s"] <= 2.5
    assert slis["slo.queue_depth"] == 0.0
    # published into the registry
    reg = obs.registry()
    assert reg.get("slo.error_ratio").value == pytest.approx(0.1)
    assert reg.get("predict.cache_hit_ratio").value \
        == pytest.approx(0.75)


def test_threshold_breach_flips_gauge_and_counts_transitions():
    t = obs_slo.SloTracker(window_s=300,
                           thresholds={"predict_p99_ms": 5.0,
                                       "error_ratio": 0.5})
    reg = obs.registry()
    # healthy: 1 ms predictions
    for _ in range(50):
        t.feed_hist("predict/call", 0.001, now=10.0)
        t.feed_count("predict.requests", now=10.0)
    t.evaluate(now=10.0)
    assert reg.get("slo.breached", slo="predict_p99_ms").value == 0.0
    assert reg.get("slo.breaches", slo="predict_p99_ms") is None
    # regress: 50 ms predictions dominate the window
    for _ in range(200):
        t.feed_hist("predict/call", 0.050, now=20.0)
    t.evaluate(now=20.0)
    assert reg.get("slo.breached", slo="predict_p99_ms").value == 1.0
    assert reg.get("slo.breaches", slo="predict_p99_ms").value == 1.0
    # still breached: the gauge stays 1, the counter does NOT re-count
    t.evaluate(now=21.0)
    assert reg.get("slo.breached", slo="predict_p99_ms").value == 1.0
    assert reg.get("slo.breaches", slo="predict_p99_ms").value == 1.0
    # recover: the slow window ages out entirely
    for _ in range(50):
        t.feed_hist("predict/call", 0.001, now=400.0)
    t.evaluate(now=400.0)
    assert reg.get("slo.breached", slo="predict_p99_ms").value == 0.0
    # re-breach counts a SECOND transition
    for _ in range(200):
        t.feed_hist("predict/call", 0.050, now=410.0)
    t.evaluate(now=410.0)
    assert reg.get("slo.breaches", slo="predict_p99_ms").value == 2.0
    # error-ratio threshold never configured data -> no false breach
    assert reg.get("slo.breached", slo="error_ratio").value == 0.0


def test_unset_thresholds_are_gauge_only():
    t = obs_slo.SloTracker(window_s=300, thresholds={})
    t.feed_hist("predict/call", 99.0, now=1.0)
    t.evaluate(now=1.0)
    assert obs.registry().get("slo.breached",
                              slo="predict_p99_ms") is None


def test_unknown_threshold_keys_are_rejected_not_misrouted():
    # a typo'd key must not silently evaluate against the wrong SLI
    t = obs_slo.SloTracker(window_s=300,
                           thresholds={"round_p99_s": 5.0,
                                       "predict_p99_ms": 10.0})
    assert t.thresholds == {"predict_p99_ms": 10.0}
    t.evaluate(now=1.0)
    assert obs.registry().get("slo.breached", slo="round_p99_s") is None


def test_drained_window_drops_gauges_instead_of_freezing():
    t = obs_slo.SloTracker(window_s=60)
    for _ in range(20):
        t.feed_hist("predict/call", 0.8, now=10.0)
    t.evaluate(now=10.0)
    reg = obs.registry()
    assert reg.get("slo.predict_p99_ms").value > 100.0
    # traffic stops; the window drains — a frozen 800 ms gauge would
    # lie to every later scrape, so it must disappear
    t.evaluate(now=500.0)
    assert reg.get("slo.predict_p99_ms") is None
    assert reg.get("slo.error_ratio") is None
    assert reg.get("slo.queue_depth") is not None   # placeholder stays


# ---------------------------------------------------------------------------
# obs wiring
# ---------------------------------------------------------------------------
def test_obs_funnels_feed_tracker_only_when_slo_enabled():
    obs.enable(metrics=True)
    with obs.span("predict/call"):
        pass
    obs.inc("predict.requests")
    assert not obs.slo_enabled()          # metrics alone: no tracker
    obs.enable(slo=True)
    assert obs.slo_enabled()
    with obs.span("predict/call"):
        pass
    obs.observe("predict/call", 0.003)
    obs.inc("predict.requests", 2)
    t = obs_slo.tracker()
    assert t.hists["predict/call"].count() == 2
    assert t.counters["predict.requests"].total() == 2.0
    # snapshot runs an evaluation period: SLO gauges appear
    names = {m["name"] for m in obs.snapshot()["metrics"]}
    assert {"slo.predict_p99_ms", "slo.queue_depth"} <= names


def test_enable_slo_implies_metrics_and_merges_thresholds():
    obs.enable(slo=True, slo_thresholds={"predict_p99_ms": 10.0})
    assert obs.enabled()
    # a later enable ADDS a threshold without dropping window state
    obs_slo.feed_hist("predict/call", 0.001)
    obs.enable(slo=True, slo_thresholds={"error_ratio": 0.2})
    t = obs_slo.tracker()
    assert t.thresholds == {"predict_p99_ms": 10.0,
                            "error_ratio": 0.2}
    assert t.hists["predict/call"].count() == 1


def test_export_state_excludes_ephemeral_slo_and_heartbeat_state():
    obs.enable(metrics=True, slo=True)
    obs.heartbeat("train")
    obs.inc("train.iterations", 3)
    obs.inc("predict.stack_cache_hits")   # windowed ratio gets data
    obs.snapshot()                        # publishes slo.* gauges
    reg_names = {m.name for m in obs.registry().metrics()}
    assert "heartbeat.train" in reg_names
    assert "predict.cache_hit_ratio" in reg_names
    assert any(n.startswith("slo.") for n in reg_names)
    saved = {m["name"] for m in obs.export_state()["metrics"]}
    assert "train.iterations" in saved
    assert not any(n.startswith(("heartbeat.", "slo.")) for n in saved)
    # the windowed cache-hit ratio is SLO-derived state too: a resumed
    # process with the tracker off must not expose a dead process's
    # frozen ratio
    assert "predict.cache_hit_ratio" not in saved


def test_heartbeat_noop_when_metrics_off():
    assert not obs.enabled()
    obs.heartbeat("train")
    assert obs.registry().get("heartbeat.train") is None


def test_clean_training_retires_train_heartbeat(tmp_path):
    """Absent heartbeat = finished; stale heartbeat = wedged/crashed.
    A clean train() must retire its stamp so an idle post-training
    process reads healthy forever; a crashed one must leave the stale
    stamp behind as the 503 signal."""
    X, y = _data()
    ds = lgb.Dataset(X, label=y)
    lgb.train(dict(PARAMS, tpu_metrics=True), ds, num_boost_round=3)
    assert obs.registry().get("heartbeat.train") is None
    ds = lgb.Dataset(X, label=y)
    with pytest.raises(lgb.LightGBMError, match="injected failure"):
        lgb.train(dict(PARAMS, tpu_metrics=True,
                       tpu_fault_inject="exn:iter=2",
                       tpu_fault_marker=str(tmp_path)),
                  ds, num_boost_round=5)
    assert obs.registry().get("heartbeat.train") is not None


def test_erroring_predicts_still_stamp_serve_liveness():
    """Liveness means "the serving loop is running", not "requests
    succeed": a process drowning in malformed requests must stay
    /healthz-green (slo.error_ratio is the alert for that), so the
    serve heartbeat stamps on ATTEMPT."""
    X, y = _data()
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train(dict(PARAMS, tpu_metrics=True), ds,
                    num_boost_round=3)
    err0 = obs.counter("predict.errors").value
    req0 = obs.counter("predict.requests").value
    with pytest.raises(lgb.LightGBMError, match="number of features"):
        bst.predict(X[:10, :3])          # wrong feature count: raises
    assert obs.registry().get("heartbeat.serve") is not None
    assert obs.counter("predict.errors").value == err0 + 1
    assert obs.counter("predict.requests").value == req0 + 1


def test_slo_window_knob_alone_starts_tracker():
    from lightgbm_tpu.config import Config
    assert not obs.slo_enabled()
    Config({"tpu_metrics": True, "tpu_slo_window_s": 60.0,
            "verbosity": -1})
    assert obs.slo_enabled()
    assert obs_slo.tracker().window_s == 60.0
