"""Test config: run everything on a virtual 8-device CPU mesh.

This is the "multi-node without a cluster" mechanism (SURVEY.md §4): the
reference spawns N localhost CLI processes for its distributed tests; we
give XLA 8 fake host devices so sharded/distributed paths execute real
collectives in-process.

``LGBM_TPU_TESTS=1`` skips the CPU pin so the suite runs against the
REAL TPU backend — this is how the Pallas-kernel equivalence tests
(test_multi_leaf_histogram.py's ``requires_tpu`` cases) execute on the
hardware they target; run ``LGBM_TPU_TESTS=1 python -m pytest tests/``
once per round. Distributed tests self-skip there (one real chip).

NOTE: this environment's site config pins ``jax_platforms=axon,cpu``
(one real TPU via tunnel), so JAX_PLATFORMS env alone is ignored — we
must override through jax.config BEFORE any device is initialized.
"""
import os

TPU_MODE = os.environ.get("LGBM_TPU_TESTS", "") == "1"

if not TPU_MODE:
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()

# persistent compilation cache: grow_tree's while_loop is expensive to
# compile; cache across test runs keeps the suite fast
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      "/tmp/lightgbm_tpu_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.1")

import jax  # noqa: E402

if not TPU_MODE:
    jax.config.update("jax_platforms", "cpu")
    assert jax.device_count() == 8, (
        f"expected 8 fake CPU devices, got {jax.devices()}")


def pytest_collection_modifyitems(config, items):
    if not TPU_MODE or jax.device_count() >= 8:
        return
    import pytest
    skip = pytest.mark.skip(
        reason="needs the 8-device CPU mesh (TPU mode has "
               f"{jax.device_count()} device(s))")
    multi_device_files = {"test_distributed.py",
                          "test_parallel_learners.py"}
    for item in items:
        if item.fspath.basename in multi_device_files:
            item.add_marker(skip)
