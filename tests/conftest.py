"""Test config: run everything on a virtual 8-device CPU mesh.

This is the "multi-node without a cluster" mechanism (SURVEY.md §4): the
reference spawns N localhost CLI processes for its distributed tests; we
give XLA 8 fake host devices so sharded/distributed paths execute real
collectives in-process.

``LGBM_TPU_TESTS=1`` skips the CPU pin so the suite runs against the
REAL TPU backend — this is how the Pallas-kernel equivalence tests
(test_multi_leaf_histogram.py's ``requires_tpu`` cases) execute on the
hardware they target; run ``LGBM_TPU_TESTS=1 python -m pytest tests/``
once per round. Distributed tests self-skip there (one real chip).

NOTE: this environment's site config pins ``jax_platforms=axon,cpu``
(one real TPU via tunnel), so JAX_PLATFORMS env alone is ignored — we
must override through jax.config BEFORE any device is initialized.
"""
import os

TPU_MODE = os.environ.get("LGBM_TPU_TESTS", "") == "1"

if not TPU_MODE:
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()

# persistent compilation cache: grow_tree's while_loop is expensive to
# compile; cache across test runs keeps the suite fast
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      "/tmp/lightgbm_tpu_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.1")

import jax  # noqa: E402
import pytest  # noqa: E402

if not TPU_MODE:
    jax.config.update("jax_platforms", "cpu")
    assert jax.device_count() == 8, (
        f"expected 8 fake CPU devices, got {jax.devices()}")


@pytest.fixture(scope="session")
def multiprocess_collectives():
    """Skip marker for platforms whose CPU backend cannot run ANY
    cross-process collective (a jaxlib limitation, not a bug in the
    code under test — this container's jaxlib is one such): two bare
    ``jax.distributed`` processes attempt one ``process_allgather``,
    once per session (session scope memoizes the probe). Tests that
    fork a REAL multi-process gang (``num_machines>1`` CLI runs,
    4-process fault-tolerance/multihost runs) request this fixture so
    tier-1 reads zero expected failures instead of known-red tests.
    Only a probe ERROR skips — an allgather that runs but returns wrong
    data is a real failure and fails every dependent test."""
    import multiprocessing as mp

    from _multihost_worker import collectives_probe_child
    from lightgbm_tpu.parallel.launch import _free_port
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    port = _free_port()
    flags = os.environ.get("XLA_FLAGS", "")
    os.environ["XLA_FLAGS"] = " ".join(
        f for f in flags.split()
        if "host_platform_device_count" not in f)
    procs = []
    try:
        for rank in range(2):
            os.environ["_LGBM_PROBE_RANK"] = str(rank)
            p = ctx.Process(target=collectives_probe_child,
                            args=(port, q))
            p.start()
            procs.append(p)
        results = [q.get(timeout=60) for _ in range(2)]
    except Exception as e:
        results = [("err", f"{type(e).__name__}: {e}")]
    finally:
        os.environ["XLA_FLAGS"] = flags
        os.environ.pop("_LGBM_PROBE_RANK", None)
        for p in procs:
            p.join(timeout=10)
            if p.is_alive():
                p.kill()
    bad = [r for r in results if r[0] != "ok"]
    if bad:
        pytest.skip("this jaxlib's CPU backend cannot run multi-process "
                    f"collectives ({bad[0][1]}); single-process "
                    f"variants still cover the code paths")
    assert all(r[1] == [0, 1] for r in results), \
        f"collectives returned wrong data: {results}"


@pytest.fixture(autouse=True)
def _obs_registry_guard(request):
    """Snapshot-and-restore the PROCESS-WIDE observability state around
    every obs-flavored test (module name contains ``obs`` or ``slo``).

    The obs registry, SLO tracker, trace buffer and metrics server are
    process globals; without this guard an obs test could leak an
    enabled registry into the rest of tier-1 (timing) or inherit
    forced counters from earlier tests (restart.attempts and friends),
    making assertions order-dependent. Non-obs modules pay one string
    check."""
    name = request.module.__name__
    if "obs" not in name and "slo" not in name:
        yield
        return
    from lightgbm_tpu import obs
    from lightgbm_tpu.obs import metrics as _om
    from lightgbm_tpu.obs import server as _osrv
    from lightgbm_tpu.obs import slo as _oslo
    from lightgbm_tpu.obs import tracing as _otr
    reg = _om.registry()
    # VALUE snapshot, not an object-reference copy: the test may
    # mutate a pre-existing metric in place (forced counters), and the
    # restore must bring the old values back, not the shared objects
    saved_state = reg.export_state()
    saved_enabled = obs.enabled()
    saved_dir = _otr._dir
    try:
        yield
    finally:
        obs.disable()
        obs.reset()
        _oslo.reset()
        _osrv.stop_server()
        _otr._dir = saved_dir
        reg.import_state(saved_state)
        if saved_enabled:
            obs.enable(metrics=True)


def pytest_collection_modifyitems(config, items):
    if not TPU_MODE or jax.device_count() >= 8:
        return
    import pytest
    skip = pytest.mark.skip(
        reason="needs the 8-device CPU mesh (TPU mode has "
               f"{jax.device_count()} device(s))")
    multi_device_files = {"test_distributed.py",
                          "test_parallel_learners.py"}
    for item in items:
        if item.fspath.basename in multi_device_files:
            item.add_marker(skip)
