"""Trace attribution (obs/trace_attr.py + scripts/trace_attr.py).

The attribution pipeline is pure parsing — so it is pinned against a
SYNTHETIC XSpace dump encoded with the same protobuf wire format the
reader decodes: known per-op durations in, exact ``copy_share`` /
``wall_busy_gap_ms`` out. Also covers the degradation contract (a
host-only trace — the CPU backend's shape — must report "nothing to
attribute", never crash the run that produced it), the gauge feed into
the obs registry, and the CLI.
"""
import json
import os
import subprocess
import sys

import pytest

from lightgbm_tpu.obs.trace_attr import (aggregate_ops, attribute,
                                         newest_xplane, parse_xspace,
                                         profile_gauges)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# protobuf wire-format ENCODER (test-side twin of the module's reader)
# ---------------------------------------------------------------------------
def _varint(x: int) -> bytes:
    out = bytearray()
    while True:
        b = x & 0x7F
        x >>= 7
        if x:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _field(num: int, payload: bytes) -> bytes:
    return _varint((num << 3) | 2) + _varint(len(payload)) + payload


def _vfield(num: int, value: int) -> bytes:
    return _varint(num << 3) + _varint(value)


def _event(mid: int, offset_ps: int, duration_ps: int,
           occurrences: int = 0) -> bytes:
    buf = (_vfield(1, mid) + _vfield(2, offset_ps)
           + _vfield(3, duration_ps))
    if occurrences:
        buf += _vfield(5, occurrences)
    return buf


def _line(name: str, timestamp_ns: int, events) -> bytes:
    buf = _field(2, name.encode()) + _vfield(3, timestamp_ns)
    for ev in events:
        buf += _field(4, ev)
    return buf


def _metadata_entry(mid: int, name: str) -> bytes:
    meta = _vfield(1, mid) + _field(2, name.encode())
    return _vfield(1, mid) + _field(2, meta)


def _plane(name: str, lines, metadata) -> bytes:
    buf = _field(2, name.encode())
    for ln in lines:
        buf += _field(3, ln)
    for entry in metadata:
        buf += _field(4, entry)
    return buf


def _synthetic_xspace() -> bytes:
    """One host plane (must be ignored) + one device plane whose
    "XLA Ops" line carries: fusion.1 60 ms, copy.3 25 ms twice via
    num_occurrences=2 at 12.5 ms, copy-start.4 10 ms, dynamic-slice.9
    5 ms -> busy 100 ms, copy 35 ms, copy_share 0.35."""
    MS = 1_000_000_000  # ps per ms
    host = _plane("/host:CPU", [
        _line("python threads", 0, [_event(1, 0, 5 * MS)]),
    ], [_metadata_entry(1, "HostWork")])
    dev = _plane("/device:TPU:0 (fake)", [
        _line("XLA Ops", 1_000, [
            _event(1, 0, 60 * MS),
            _event(2, 60 * MS, 12_500_000_000, occurrences=2),
            _event(3, 85 * MS, 10 * MS),
            _event(4, 95 * MS, 5 * MS),
        ]),
        _line("Steps", 0, []),
    ], [
        _metadata_entry(1, "fusion.1"),
        _metadata_entry(2, "%copy.3"),
        _metadata_entry(3, "copy-start.4"),
        _metadata_entry(4, "dynamic-slice.9"),
    ])
    return _field(1, host) + _field(1, dev)


@pytest.fixture()
def dump_dir(tmp_path):
    # jax.profiler's layout: <dir>/plugins/profile/<ts>/<host>.xplane.pb
    d = tmp_path / "plugins" / "profile" / "2026_08_04"
    d.mkdir(parents=True)
    (d / "host.xplane.pb").write_bytes(_synthetic_xspace())
    return str(tmp_path)


def test_parse_and_aggregate_synthetic_dump():
    planes = parse_xspace(_synthetic_xspace())
    assert [p["name"] for p in planes] == ["/host:CPU",
                                           "/device:TPU:0 (fake)"]
    agg = aggregate_ops(planes)
    assert agg is not None
    assert agg["device_plane"] == "/device:TPU:0 (fake)"
    # name resolution through the metadata map, occurrences multiplied
    assert agg["ops"]["%copy.3"] == [25_000_000_000.0, 2]
    assert agg["busy_ps"] == 100_000_000_000
    # copy.3 + copy-start.4 count as copies; dynamic-slice does not
    assert agg["copy_ps"] == 35_000_000_000


def test_attribute_shares_and_gap(dump_dir):
    res = attribute(dump_dir, iters=10, wall_ms=150.0)
    assert res["found"]
    assert res["source"].endswith("host.xplane.pb")
    assert res["busy_ms"] == pytest.approx(100.0)
    assert res["copy_share"] == pytest.approx(0.35)
    # (150 wall - 100 busy) / 10 iters
    assert res["wall_busy_gap_ms"] == pytest.approx(5.0)
    # ops sorted by busy descending, share sums to 1
    assert res["ops"][0]["name"] == "fusion.1"
    assert sum(op["share"] for op in res["ops"]) == pytest.approx(1.0)


def test_comm_share_buckets_collectives(tmp_path):
    """Collectives — sync forms and XLA's async -start/-done splits —
    bucket into ``comm_share``; compute fusions do not, and the comm
    and copy buckets stay disjoint."""
    MS = 1_000_000_000
    dev = _plane("/device:TPU:0", [
        _line("XLA Ops", 0, [
            _event(1, 0, 55 * MS),
            _event(2, 55 * MS, 30 * MS),
            _event(3, 85 * MS, 10 * MS),
            _event(4, 95 * MS, 5 * MS),
        ]),
    ], [
        _metadata_entry(1, "fusion.2"),
        _metadata_entry(2, "%all-reduce.7"),
        _metadata_entry(3, "all-reduce-start.9"),
        _metadata_entry(4, "copy.11"),
    ])
    f = tmp_path / "comm.xplane.pb"
    f.write_bytes(_field(1, dev))
    res = attribute(str(f))
    assert res["found"]
    assert res["busy_ms"] == pytest.approx(100.0)
    # all-reduce.7 + all-reduce-start.9; neither fusion nor copy
    assert res["comm_ms"] == pytest.approx(40.0)
    assert res["comm_share"] == pytest.approx(0.40)
    assert res["copy_share"] == pytest.approx(0.05)


def test_newest_xplane_picks_latest(tmp_path):
    d = tmp_path / "plugins" / "profile"
    d.mkdir(parents=True)
    old = d / "old.xplane.pb"
    new = d / "new.xplane.pb"
    old.write_bytes(b"")
    new.write_bytes(b"")
    os.utime(old, (1, 1))
    os.utime(new, (2, 2))
    assert newest_xplane(str(tmp_path)) == str(new)
    assert newest_xplane(str(tmp_path / "missing")) is None


def test_host_only_trace_degrades_not_crashes(tmp_path):
    """The CPU-backend shape: a dump whose only plane is host threads
    must come back found=False with a reason — the run that produced
    the trace keeps going."""
    MS = 1_000_000_000
    host_only = _field(1, _plane("/host:CPU", [
        _line("python threads", 0, [_event(1, 0, MS)]),
    ], [_metadata_entry(1, "HostWork")]))
    f = tmp_path / "host.xplane.pb"
    f.write_bytes(host_only)
    res = attribute(str(f))
    assert not res["found"]
    assert "no device plane" in res["reason"]
    # and a truncated/garbage dump reports, never raises
    g = tmp_path / "garbage.xplane.pb"
    g.write_bytes(b"\x0a\xff\xff\xff")
    assert not attribute(str(g))["found"]


def test_profile_gauges_feed_obs_registry(dump_dir):
    from lightgbm_tpu import obs
    res = profile_gauges(dump_dir, iters=10, wall_ms=150.0)
    assert res["found"]
    snap = obs.snapshot()
    vals = {m["name"]: m["value"] for m in snap["metrics"]
            if not m.get("labels")}
    assert vals["train.copy_share"] == pytest.approx(0.35)
    # the synthetic dump has no collectives: comm_share feeds as 0,
    # not as a missing gauge (obs_trend skips missing signals)
    assert vals["train.comm_share"] == pytest.approx(0.0)
    assert vals["train.wall_busy_gap_ms"] == pytest.approx(5.0)
    # degradation feeds nothing and reports why
    missing = profile_gauges(os.path.join(dump_dir, "nope"))
    assert not missing["found"]


def test_cli_text_and_json(dump_dir):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "scripts",
                                      "trace_attr.py"),
         dump_dir, "--iters", "10", "--wall-ms", "150", "--json"],
        capture_output=True, text=True, env=env, cwd=REPO_ROOT)
    assert out.returncode == 0, out.stderr
    res = json.loads(out.stdout)
    assert res["copy_share"] == pytest.approx(0.35)
    # text mode renders the table + the gap line
    out2 = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "scripts",
                                      "trace_attr.py"),
         dump_dir, "--iters", "10", "--wall-ms", "150"],
        capture_output=True, text=True, env=env, cwd=REPO_ROOT)
    assert out2.returncode == 0, out2.stderr
    assert "%copy (loop-state copies)" in out2.stdout
    assert "5.00 ms/iter" in out2.stdout
    # nothing to attribute -> exit 3 (the CPU-trace contract)
    out3 = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "scripts",
                                      "trace_attr.py"),
         os.path.join(dump_dir, "missing")],
        capture_output=True, text=True, env=env, cwd=REPO_ROOT)
    assert out3.returncode == 3
