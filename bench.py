"""Benchmark: boosting iters/sec on synthetic Higgs-like data.

Driver contract: print ONE JSON line
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}``.

Config mirrors BASELINE.json's flagship: binary classification, 28 dense
features, num_leaves=127, max_bin=255. The dataset is synthesized (no
network in this environment; Higgs itself is a download). Default 1M
rows — matching the "Higgs-1M CPU hist baseline" config shape; pass
``--rows 10000000`` for the flagship Higgs-10M shape (BASELINE.json's
headline metric), which also reports binning time and peak HBM.

The default run uses GOSS — the reference's own flagship sampling
technique (the NeurIPS'17 paper's core contribution) with this repo's
histogram-only row compaction — which is both ~2x faster than plain
full-row scans AND reaches a better held-out AUC at equal iterations
(0.9511 vs 0.9478; docs/perf.md). Pass --plain for full-row scans.

Protocol: the model trains warmup+iters rounds, the held-out AUC is
measured THERE (fixed iteration count, comparable across runs), then a
second timed window re-times the same chunk length and the BEST window
is reported (steady-state throughput; a single window through the
tunneled chip occasionally catches a stall).

Extra flags (all optional; defaults reproduce the driver run):
  --rows N --holdout N --iters N --leaf-batch K --hist-mode pool|rebuild
  --quant (use_quantized_grad) --plain (full-row scans)
  --goss (explicit GOSS override, the default; last of --plain/--goss
  wins)

vs_baseline: BASELINE.md holds NO verified reference numbers (empty
mount). We compare against 1.0 iters/sec — the ballpark of CPU
hist-LightGBM on Higgs-1M-class data per BASELINE.md's unverified
recollection table — so vs_baseline > 1 means faster than CPU LightGBM.
"""
import argparse
import json
import sys
import time

import numpy as np

N_FEATURES = 28
NUM_LEAVES = 127
MAX_BIN = 255
CPU_LIGHTGBM_BASELINE_ITERS_PER_SEC = 1.0  # UNVERIFIED, see BASELINE.md


def synth_higgs(n, f, seed=0):
    """Higgs-like: mixture of informative kinematic-ish features."""
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    w = rng.normal(size=f)
    logit = (X @ w * 0.5 + 0.8 * X[:, 0] * X[:, 1]
             + 0.5 * np.abs(X[:, 2]) - 0.4)
    y = (logit + rng.normal(scale=1.0, size=n) > 0).astype(np.float64)
    return X.astype(np.float64), y


def peak_hbm_gib():
    try:
        import jax
        stats = jax.devices()[0].memory_stats() or {}
        peak = stats.get("peak_bytes_in_use")
        return None if peak is None else round(peak / 2**30, 2)
    except Exception:
        return None


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1_000_000)
    ap.add_argument("--holdout", type=int, default=100_000)
    ap.add_argument("--iters", type=int, default=40)
    # warmup must match the timed chunk length so the fused scan is
    # compiled exactly once, outside the timed region
    ap.add_argument("--warmup", type=int, default=None)
    ap.add_argument("--leaf-batch", type=int, default=None)
    ap.add_argument("--hist-mode", choices=["pool", "rebuild"],
                    default=None)
    ap.add_argument("--quant", action="store_true")
    ap.add_argument("--goss", action="store_true", default=True)
    ap.add_argument("--plain", dest="goss", action="store_false",
                    help="disable GOSS (full-row scans)")
    ap.add_argument("--precise", action="store_true",
                    help="tpu_double_precision_hist (f32 histograms)")
    args = ap.parse_args()

    import lightgbm_tpu as lgb
    from lightgbm_tpu.boosting.gbdt import GBDT
    from lightgbm_tpu.config import Config

    X, y = synth_higgs(args.rows + args.holdout, N_FEATURES)
    X, X_ho = X[:args.rows], X[args.rows:]
    y, y_ho = y[:args.rows], y[args.rows:]
    t_bin = time.time()
    ds = lgb.Dataset(X, label=y)
    params = {"objective": "binary", "num_leaves": NUM_LEAVES,
              "max_bin": MAX_BIN, "learning_rate": 0.1,
              "verbosity": -1}
    if args.leaf_batch is not None:
        params["tpu_leaf_batch"] = args.leaf_batch
    if args.hist_mode is not None:
        params["tpu_hist_mode"] = args.hist_mode
    if args.quant:
        params["use_quantized_grad"] = True
    if args.goss:
        params["data_sample_strategy"] = "goss"
    if args.precise:
        params["tpu_double_precision_hist"] = True
    cfg = Config(params)
    eng = GBDT(cfg, ds)
    bin_time = time.time() - t_bin

    # warmup (jit compile + cache); same chunk length as the timed run
    # so the fused scan is compiled exactly once. GOSS keeps the first
    # 1/learning_rate iterations unsampled (goss.hpp warmup), so its
    # warmup extends past them to reach the fused GOSS chunk.
    if args.warmup is None:
        args.warmup = args.iters + (10 if args.goss else 0)
    eng.train_chunk(args.warmup)
    import jax
    jax.block_until_ready(eng.score)

    t0 = time.time()
    eng.train_chunk(args.iters)
    jax.block_until_ready(eng.score)
    iters_per_sec = args.iters / (time.time() - t0)

    # held-out AUC at the FIXED warmup+iters round count (comparable
    # across runs/configs), BEFORE the re-timing window below
    from lightgbm_tpu.metric import AUCMetric
    pred = eng.predict(X_ho)
    auc = AUCMetric(cfg).eval(pred, y_ho, None)[0][1]

    # second timed window, best wins: a single window through the
    # tunneled chip occasionally catches a stall/late compile (observed
    # 5.3 vs 16.6 it/s on back-to-back identical runs)
    t0 = time.time()
    eng.train_chunk(args.iters)
    jax.block_until_ready(eng.score)
    iters_per_sec = max(iters_per_sec, args.iters / (time.time() - t0))

    shape_tag = ("higgs1m-synth" if args.rows == 1_000_000
                 else f"higgs{args.rows // 1_000_000}m-synth"
                 if args.rows % 1_000_000 == 0
                 else f"higgs{args.rows}-synth")
    extras = "; goss" if args.goss else "; full-rows"
    if args.quant:
        extras += "+quantized"
    peak = peak_hbm_gib()
    if peak is not None:
        extras += f"; peak_hbm_gib={peak}"
    result = {
        "metric": ("boosting_iters_per_sec "
                   f"({shape_tag} nl={NUM_LEAVES} mb={MAX_BIN}; "
                   f"holdout_auc={auc:.4f}; binning_s={bin_time:.1f}"
                   f"{extras})"),
        "value": round(iters_per_sec, 4),
        "unit": "iters/sec",
        "vs_baseline": round(
            iters_per_sec / CPU_LIGHTGBM_BASELINE_ITERS_PER_SEC, 4),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
