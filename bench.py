"""Benchmark: boosting iters/sec on synthetic Higgs-like data.

Driver contract: print ONE JSON line
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}``.

Config mirrors BASELINE.json's flagship headline: Higgs-10M, binary
classification, 28 dense features, num_leaves=127, max_bin=255. The
dataset is synthesized (no network in this environment; Higgs itself
is a download). Default 10M rows with GOSS + quantized gradients —
both reference-native speed features (goss.hpp + the gradient
discretizer) — which reach a BETTER held-out AUC than plain full-row
f32 scans at this shape (10M: 0.9467 vs 0.9433; 1M at equal 90
rounds: 0.9514 vs 0.9478 — measured round 4). For continuity with
rounds 1-3 the same run also times the higgs-1M PLAIN configuration
and embeds it in the metric string (``plain1m=...``), so protocol
changes can never masquerade as speedups.

Protocol (round-4 revision, addressing ADVICE r3):
- the model trains warmup+iters rounds with warmup = iters + 10 for
  EVERY config (GOSS needs the +10 to get past its unsampled first
  1/learning_rate rounds; plain keeps the same total so AUCs are
  at identical round counts);
- held-out AUC is measured at that fixed round count, comparable
  across configs and rounds;
- then THREE equal timed windows re-run the same chunk length and the
  MEDIAN is reported (tagged ``median-of-3`` in the metric string; a
  single window through the tunneled chip occasionally catches a
  stall — observed 5.3 vs 16.6 it/s back-to-back — and best-of-N
  would bias up).

Quality guards: (1) the main holdout AUC above; (2) a second guard
dataset (``synth_guard``) with strong interactions, 10% NaNs and two
categorical columns, trained at 200k rows — its AUC collapses if
categorical splits or missing-value routing regress (measured on the
v5e: 0.868 with categorical handling, 0.836 with categoricals treated
numeric; the 0.85 floor sits between).
The main synthetic is near-linearly separable (holdout AUC ~0.95 where
real Higgs sits ~0.845, BASELINE.md) and cannot catch those paths;
the guard exists for exactly that. Neither guard can catch
regressions confined to ranking/multiclass/DART paths — those live in
benchmarks/suite.py.

Extra flags (defaults reproduce the driver run):
  --rows N --holdout N --iters N --leaf-batch K --hist-mode pool|rebuild
  --plain (full-row f32 scans; also disables quantization)
  --goss/--quant (re-enable pieces after --plain; last wins)
  --no-guard2 / --no-plain1m (skip the secondary sections)

vs_baseline: BASELINE.md holds NO verified reference numbers (empty
mount). The ballpark comparator is CPU hist-LightGBM ~1.0 it/s at
Higgs-1M (BASELINE.md recollection), scaled linearly to 0.1 at 10M
and doubled for GOSS (~2x per the NeurIPS'17 ablations) -> 0.2
iters/sec for the default config. All UNVERIFIED; vs_baseline > 1
means faster than that recollection of CPU LightGBM.
"""
import argparse
import json
import statistics
import sys
import time

import numpy as np

N_FEATURES = 28
NUM_LEAVES = 127
MAX_BIN = 255
# UNVERIFIED ballparks, see module docstring + BASELINE.md
CPU_LIGHTGBM_BASELINE = {
    (True, 1_000_000): 2.0,     # (goss, rows): CPU GOSS at 1M
    (False, 1_000_000): 1.0,    # CPU plain hist at 1M
    (True, 10_000_000): 0.2,
    (False, 10_000_000): 0.1,
}


def synth_higgs(n, f, seed=0):
    """Higgs-like: mixture of informative kinematic-ish features.
    UNCHANGED since round 1 (headline continuity) — near-linear, no
    NaNs/categoricals; see synth_guard for those paths."""
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    w = rng.normal(size=f)
    logit = (X @ w * 0.5 + 0.8 * X[:, 0] * X[:, 1]
             + 0.5 * np.abs(X[:, 2]) - 0.4)
    y = (logit + rng.normal(scale=1.0, size=n) > 0).astype(np.float64)
    return X.astype(np.float64), y


def synth_guard(n, seed=7):
    """Categorical/NaN/interaction guard dataset: 10 numeric features
    (pairwise interactions dominate), one 12-way and one 40-way
    categorical with target-dependent effects, 10% NaNs in half the
    numeric columns (informative missingness)."""
    rng = np.random.default_rng(seed)
    Xn = rng.normal(size=(n, 10)).astype(np.float64)
    c1 = rng.integers(0, 12, size=n)
    c2 = rng.integers(0, 40, size=n)
    eff1 = rng.normal(size=12)[c1] * 1.2
    eff2 = rng.normal(size=40)[c2] * 0.8
    logit = (1.0 * Xn[:, 0] * Xn[:, 1] + 0.9 * Xn[:, 2] * Xn[:, 3]
             - 0.7 * Xn[:, 4] * np.abs(Xn[:, 5]) + eff1 + eff2)
    # informative missingness: NaN rows carry signal
    for j in range(5):
        miss = rng.uniform(size=n) < 0.10
        logit = logit + np.where(miss, 0.6, 0.0)
        Xn[miss, j] = np.nan
    y = (logit + rng.normal(scale=1.0, size=n) > 0).astype(np.float64)
    X = np.column_stack([Xn, c1.astype(np.float64),
                         c2.astype(np.float64)])
    return X, y


def peak_hbm_gib():
    try:
        import jax
        stats = jax.devices()[0].memory_stats() or {}
        peak = stats.get("peak_bytes_in_use")
        return None if peak is None else round(peak / 2**30, 2)
    except Exception:
        return None


def _snap_gauge(snap, name):
    """Read one gauge value back out of an obs snapshot dict (the
    metric line below is composed from the SNAPSHOT, not from local
    variables, so the numbers in BENCH_*.json and in --metrics-json can
    never disagree)."""
    for m in snap["metrics"]:
        if m["name"] == name and not m.get("labels"):
            return m.get("value")
    return None


def run_config(X, y, X_ho, y_ho, params, iters, warmup, windows=3,
               cat_features="auto", measure_predict=True):
    """Train warmup+iters rounds, AUC there, then median of N timed
    windows of the same chunk length."""
    import jax

    import lightgbm_tpu as lgb
    from lightgbm_tpu.boosting.gbdt import GBDT
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.metric import AUCMetric

    # split timers (VERDICT r4): construct_s is the host-side binning
    # (native C++ since r4, 13.5x); engine_init_s is GBDT.__init__ —
    # device upload of the bin matrices + score/partition init — which
    # dominates at 10M. perf.md reports the same decomposition.
    t0 = time.time()
    ds = lgb.Dataset(X, label=y, categorical_feature=cat_features)
    ds.construct()
    construct_s = time.time() - t0
    cfg = Config(params)
    t0 = time.time()
    eng = GBDT(cfg, ds)
    engine_init_s = time.time() - t0
    # warm the REMAINDER first (it absorbs GOSS's unsampled first
    # 1/lr rounds), then one full timed-length chunk: that second call
    # is the one that compiles the fused scan the windows reuse —
    # running it after the GOSS activation boundary matters, else the
    # fused GOSS chunk would first compile inside timed window 1
    first = (warmup - iters) if warmup > iters else min(iters, warmup)
    t0 = time.time()
    eng.train_chunk(first)
    jax.block_until_ready(eng.score)
    first_chunk_s = time.time() - t0
    # time-to-first-iteration: construct + engine init + the first
    # (compile-inclusive) boosting dispatch — the serving-relevant
    # startup cost a production retrain pays on EVERY job. The first
    # chunk runs a few real iterations too; at cold-compile scale that
    # overcount is noise, and warm-cache runs shrink it to exactly
    # those iterations.
    bin_time = (construct_s, engine_init_s,
                construct_s + engine_init_s + first_chunk_s)
    if warmup > iters:
        eng.train_chunk(min(iters, warmup))
        jax.block_until_ready(eng.score)
    # --profile-dir: jax.profiler trace around the FIRST timed window
    # (the steady state, matching the r5 attribution protocol), then
    # the raw-XSpace attribution feeds train.copy_share /
    # train.wall_busy_gap_ms — read back off the one snapshot below
    prof_dir = str(getattr(cfg, "tpu_profile_dir", "") or "").strip()
    if prof_dir:
        jax.profiler.start_trace(prof_dir)
    rates = []
    t0 = time.time()
    eng.train_chunk(iters)
    jax.block_until_ready(eng.score)
    window_s = time.time() - t0
    rates.append(iters / window_s)
    if prof_dir:
        # wall measured BEFORE stop_trace: writing the dump to disk is
        # not part of the traced window's wall time
        jax.profiler.stop_trace()
        from lightgbm_tpu.obs.trace_attr import profile_gauges
        profile_gauges(prof_dir, iters=iters, wall_ms=window_s * 1e3)
    # held-out AUC at the fixed warmup+iters round count (equal across
    # configs), between the timed windows so it inflates none of them
    pred = eng.predict(X_ho)
    auc = AUCMetric(cfg).eval(pred, y_ho, None)[0][1]
    # serving throughput (the inference engine's steady state: cached
    # device forest + bucketed batch shapes; benchmarks/predict_bench.py
    # has the full grid): median rows/sec over repeat 10k-row predicts,
    # after the warm call above — main config only, the continuity/
    # guard runs discard it
    predict_rps = None
    shap_rps = None
    if measure_predict:
        n_pred = min(10_000, len(X_ho))
        eng.predict(X_ho[:n_pred])                # warm this bucket
        pred_rates = []
        for _ in range(3):
            t0 = time.time()
            eng.predict(X_ho[:n_pred])
            pred_rates.append(n_pred / (time.time() - t0))
        predict_rps = statistics.median(pred_rates)
        # explain throughput (device SHAP: cached path tables + the
        # same bucketed shapes; docs/perf.md "Device SHAP") — a small
        # subset, SHAP programs are O(depth) heavier than predicts
        n_shap = min(8_000, len(X_ho))
        eng.predict_contrib(X_ho[:n_shap])        # tables + compile
        shap_rates = []
        for _ in range(3):
            t0 = time.time()
            eng.predict_contrib(X_ho[:n_shap])
            shap_rates.append(n_shap / (time.time() - t0))
        shap_rps = statistics.median(shap_rates)
    for _ in range(windows - 1):
        t0 = time.time()
        eng.train_chunk(iters)
        jax.block_until_ready(eng.score)
        rates.append(iters / (time.time() - t0))
    from lightgbm_tpu import obs as _obs
    _obs.set_gauge("bench.hist_partition",
                   float(getattr(eng, "hist_partition", False)),
                   force=True)
    return statistics.median(rates), auc, bin_time, predict_rps, shap_rps


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=10_000_000)
    ap.add_argument("--holdout", type=int, default=None)
    ap.add_argument("--iters", type=int, default=40)
    # warmup matches the timed chunk length (+10 so GOSS gets past its
    # unsampled first 1/lr rounds) for EVERY config -> equal-round AUCs
    ap.add_argument("--warmup", type=int, default=None)
    ap.add_argument("--windows", type=int, default=3)
    ap.add_argument("--leaf-batch", type=int, default=None)
    ap.add_argument("--hist-mode", choices=["pool", "rebuild"],
                    default=None)
    class _Plain(argparse.Action):
        def __call__(self, parser, ns, values, option_string=None):
            ns.goss = ns.quant = False   # parse-time: later flags win
    ap.add_argument("--quant", action="store_true", default=True)
    ap.add_argument("--no-quant", dest="quant", action="store_false")
    ap.add_argument("--goss", action="store_true", default=True)
    ap.add_argument("--plain", action=_Plain, nargs=0,
                    help="full-row f32 scans (disables GOSS + quant; "
                         "a later --goss/--quant re-enables that piece)")
    ap.add_argument("--precise", action="store_true",
                    help="tpu_double_precision_hist (f32 histograms)")
    ap.add_argument("--partition", choices=["auto", "true", "false"],
                    default="auto",
                    help="leaf-ordered row partition "
                         "(tpu_hist_partition; docs/perf.md "
                         "'Partitioned histograms'): histograms scan "
                         "only the elected children's row spans")
    ap.add_argument("--ingest", choices=["auto", "device", "host"],
                    default="auto",
                    help="bin-assignment path for Dataset.construct "
                         "(tpu_ingest_device; docs/perf.md 'Ingest')")
    ap.add_argument("--compile-cache", type=str, default="",
                    help="persistent XLA compile cache dir "
                         "(tpu_compile_cache_dir): a second run "
                         "reloads programs instead of recompiling — "
                         "watch ttfi_s collapse")
    ap.add_argument("--no-donate", dest="donate", action="store_false",
                    default=True,
                    help="disable boosting-carry buffer donation "
                         "(tpu_donate=false) — the A/B arm for the "
                         "loop-state %%copy squeeze (docs/perf.md "
                         "'Iteration floor'); the metric line tags "
                         "donate=off")
    ap.add_argument("--profile-dir", type=str, default="",
                    help="jax.profiler trace dir for the first timed "
                         "window (tpu_profile_dir); the raw-XSpace "
                         "attribution (scripts/trace_attr.py) feeds "
                         "copy_share= / wall_busy_gap_ms= on the "
                         "metric line")
    ap.add_argument("--no-guard2", dest="guard2", action="store_false",
                    default=True)
    ap.add_argument("--no-plain1m", dest="plain1m",
                    action="store_false", default=True)
    ap.add_argument("--smoke", action="store_true",
                    help="pre-snapshot gate mode (scripts/check.sh): "
                         "single window, skip plain1m + guard2")
    ap.add_argument("--stream-rows", type=int, default=200_000,
                    help="rows for the streamed-training probe "
                         "(tpu_streaming=true, sharded over local "
                         "devices when >1; docs/perf.md 'Streamed x "
                         "sharded'). Emits stream_shards= / "
                         "stream_rows_per_sec= / allreduce_bytes= on "
                         "the metric line; 0 disables")
    ap.add_argument("--no-stream-overlap", dest="stream_overlap",
                    action="store_false", default=True,
                    help="run the streamed probe with "
                         "tpu_stream_overlap=false (synchronous "
                         "per-block dispatch) — the A/B arm for the "
                         "collective-hiding pipeline (docs/perf.md "
                         "'Communication/compute overlap'); the "
                         "metric line tags overlap=off")
    ap.add_argument("--metrics-json", type=str, default="",
                    help="append one obs metrics-snapshot JSONL line "
                         "(docs/observability.md schema) to PATH; also "
                         "enables tpu_metrics collection for the run")
    ap.add_argument("--metrics-port", type=int, default=0,
                    help="serve live GET /metrics | /metrics.json | "
                         "/healthz | /readyz on 127.0.0.1:PORT for the "
                         "duration of the run (tpu_metrics_port "
                         "semantics; scrape a long bench mid-flight)")
    args = ap.parse_args()
    if args.smoke:
        args.windows = 1
        args.plain1m = args.guard2 = False
        # keep the pre-snapshot gate fast: the streamed probe still
        # runs (the gate is where its trajectory lands) but smaller
        args.stream_rows = min(args.stream_rows, 100_000)
    if args.holdout is None:
        args.holdout = max(100_000, args.rows // 20)
    if args.warmup is None:
        args.warmup = args.iters + 10

    X, y = synth_higgs(args.rows + args.holdout, N_FEATURES)
    X, X_ho = X[:args.rows], X[args.rows:]
    y, y_ho = y[:args.rows], y[args.rows:]
    params = {"objective": "binary", "num_leaves": NUM_LEAVES,
              "max_bin": MAX_BIN, "learning_rate": 0.1,
              "verbosity": -1}
    if args.leaf_batch is not None:
        params["tpu_leaf_batch"] = args.leaf_batch
    if args.hist_mode is not None:
        params["tpu_hist_mode"] = args.hist_mode
    # explicit either way: tpu_auto_quantize would otherwise flip the
    # un-set case back on at >=500k rows, making --no-quant a no-op
    params["use_quantized_grad"] = bool(args.quant)
    if args.goss:
        params["data_sample_strategy"] = "goss"
    if args.precise:
        params["tpu_double_precision_hist"] = True
    if args.ingest != "auto":
        params["tpu_ingest_device"] = ("true" if args.ingest == "device"
                                       else "false")
    params["tpu_hist_partition"] = args.partition
    if not args.donate:
        params["tpu_donate"] = "false"
    if args.profile_dir:
        params["tpu_profile_dir"] = args.profile_dir
    if args.compile_cache:
        params["tpu_compile_cache_dir"] = args.compile_cache
    from lightgbm_tpu import obs
    if args.metrics_json:
        obs.enable(metrics=True)
    if args.metrics_port:
        # live mid-run scraping: rolling SLO gauges + heartbeats on a
        # localhost endpoint (the same plane tpu_metrics_port serves)
        from lightgbm_tpu.obs.server import start_server
        obs.enable(metrics=True, slo=True)
        start_server(args.metrics_port)

    ips, auc, bin_time, predict_rps, shap_rps = run_config(
        X, y, X_ho, y_ho, params, args.iters, args.warmup, args.windows)
    # headline measurements become forced obs gauges, and the metric
    # line below reads them back from ONE snapshot — the snapshot is
    # the authority, the printed line a view of it (same keys as ever,
    # so BENCH_*.json parsing is unchanged)
    obs.set_gauge("bench.iters_per_sec", ips, force=True)
    obs.set_gauge("bench.holdout_auc", auc, force=True)
    obs.set_gauge("bench.construct_s", bin_time[0], force=True)
    obs.set_gauge("bench.engine_init_s", bin_time[1], force=True)
    obs.set_gauge("bench.ttfi_s", bin_time[2], force=True)
    obs.set_gauge("bench.predict_rps", predict_rps, force=True)
    obs.set_gauge("bench.shap_rows_per_sec", shap_rps, force=True)

    # continuity figure: the rounds-1..3 headline config (higgs-1M,
    # plain full-row f32) timed in the same process on the main run's
    # holdout rows
    if args.plain1m and args.rows >= 1_000_000 and (
            args.rows != 1_000_000 or args.goss or args.quant):
        n1 = 1_000_000
        p1 = {"objective": "binary", "num_leaves": NUM_LEAVES,
              "max_bin": MAX_BIN, "learning_rate": 0.1,
              "verbosity": -1, "use_quantized_grad": False}
        # 40-iteration chunks: shorter ones fall below tpu_fuse_iters
        # and pay per-iteration dispatch (measured 2x slower)
        ips1, auc1, _, _, _ = run_config(
            X[:n1], y[:n1], X_ho[:100_000], y_ho[:100_000], p1,
            40, 50, windows=3, measure_predict=False)
        obs.set_gauge("bench.plain1m_iters_per_sec", ips1, force=True)
        obs.set_gauge("bench.plain1m_auc", auc1, force=True)

    # categorical/NaN/interaction guard (see module docstring)
    if args.guard2:
        Xg, yg = synth_guard(250_000)
        gp = {"objective": "binary", "num_leaves": 63, "max_bin": 255,
              "learning_rate": 0.1, "verbosity": -1}
        g_ips, g_auc, _, _, _ = run_config(Xg[:200_000], yg[:200_000],
                                        Xg[200_000:], yg[200_000:], gp,
                                        10, 40, windows=1,
                                        cat_features=[10, 11],
                                        measure_predict=False)
        obs.set_gauge("bench.guard2_auc", g_auc, force=True)

    # streamed-training trajectory (docs/perf.md "Streamed x sharded"):
    # a small forced-streaming train — sharded over the local devices
    # when the platform has more than one — so BENCH_*.json carries
    # stream_rows_per_sec / allreduce_bytes alongside the resident
    # headline instead of an empty streamed history
    if args.stream_rows > 0:
        import jax
        import lightgbm_tpu as lgb
        ns = min(args.rows, args.stream_rows)
        sp = {"objective": "binary", "num_leaves": NUM_LEAVES,
              "max_bin": MAX_BIN, "learning_rate": 0.1,
              "verbosity": -1, "tpu_streaming": "true",
              "tpu_stream_block_rows": 1 << 16,
              "tpu_stream_overlap":
                  "auto" if args.stream_overlap else "false"}
        shards = max(1, jax.local_device_count())
        if shards > 1:
            sp["tree_learner"] = "data"
            sp["tpu_mesh_shape"] = shards
        s_trees = 4
        sds = lgb.Dataset(X[:ns], label=y[:ns], params=dict(sp))
        t0 = time.time()
        sbst = lgb.train(sp, sds, num_boost_round=s_trees)
        s_secs = max(time.time() - t0, 1e-9)
        cs = sbst.engine.comm_stats
        obs.set_gauge("bench.stream_shards", sbst.engine.R, force=True)
        obs.set_gauge("bench.stream_rows_per_sec",
                      ns * s_trees / s_secs, force=True)
        obs.set_gauge("bench.stream_allreduce_bytes",
                      cs["allreduce_bytes"], force=True)
        obs.set_gauge("bench.stream_overlap",
                      1.0 if args.stream_overlap else 0.0, force=True)
        del sbst, sds

    peak = peak_hbm_gib()
    if peak is not None:
        obs.set_gauge("bench.peak_hbm_gib", peak, force=True)

    # ONE snapshot is the source for the metric line, the optional
    # JSONL dump, and (with tpu_metrics on) the full phase-timer /
    # cache-hit / compile-gauge picture of the run
    snap = obs.snapshot()
    if args.metrics_json:
        obs.dump_jsonl(args.metrics_json, snap)

    ips = _snap_gauge(snap, "bench.iters_per_sec")
    extras = "; goss" if args.goss else "; full-rows"
    if args.quant:
        extras += "+quantized"
    extras += f"; median-of-{args.windows}"
    extras += (f"; predict_rps="
               f"{_snap_gauge(snap, 'bench.predict_rps'):.0f}")
    v = _snap_gauge(snap, "bench.shap_rows_per_sec")
    if v is not None:
        # device-SHAP explain throughput on the same holdout rows
        extras += f"; shap_rps={v:.0f}"
    v = _snap_gauge(snap, "bench.hist_partition")
    extras += f"; partition={'on' if v else 'off'}"
    if not args.donate:
        # the --no-donate A/B arm tags itself so a pasted metric line
        # can never pass an undonated number off as the flagship
        extras += "; donate=off"
    v = _snap_gauge(snap, "train.copy_share")
    if v is not None:
        # --profile-dir attribution (scripts/trace_attr.py): fraction
        # of device busy in loop-state %copy ops — the signal the
        # donation pass squeezes
        extras += f"; copy_share={v:.4f}"
    v = _snap_gauge(snap, "train.comm_share")
    if v is not None:
        # collective busy share from the same attribution — read with
        # the gap: overlap keeps comm busy, shrinks the gap
        extras += f"; comm_share={v:.4f}"
    v = _snap_gauge(snap, "train.wall_busy_gap_ms")
    if v is not None:
        # per-iter wall-vs-busy gap: the stall residue the overlap
        # pipeline (and the donation pass before it) squeezes — carried
        # whenever attribution ran, not only when copy_share did
        extras += f"; wall_busy_gap_ms={v:.2f}"
    v = _snap_gauge(snap, "hist.rows_scanned")
    if v:
        # the structural win the partition exists for: total rows the
        # histogram scans touched (masked = n_pad x rounds)
        extras += f"; hist_rows_scanned={v:.3g}"
    v = _snap_gauge(snap, "bench.stream_rows_per_sec")
    if v is not None:
        # the streamed-training trajectory: rows x trees per second on
        # the out-of-core path, the shard count it ran at, and the
        # per-level collective payload it moved
        extras += (
            f"; stream_shards="
            f"{int(_snap_gauge(snap, 'bench.stream_shards'))}"
            f"; overlap="
            f"{'on' if _snap_gauge(snap, 'bench.stream_overlap') else 'off'}"
            f"; stream_rows_per_sec={v:.0f}"
            f"; allreduce_bytes="
            f"{int(_snap_gauge(snap, 'bench.stream_allreduce_bytes'))}")
    v = _snap_gauge(snap, "bench.plain1m_iters_per_sec")
    if v is not None:
        extras += (f"; plain1m={v:.2f}@auc"
                   f"{_snap_gauge(snap, 'bench.plain1m_auc'):.4f}"
                   f"(median-of-3)")
    v = _snap_gauge(snap, "bench.guard2_auc")
    if v is not None:
        extras += f"; guard2_auc={v:.4f}"
        if v < 0.85:
            extras += " GUARD2_BELOW_FLOOR(0.85)"
    v = _snap_gauge(snap, "bench.peak_hbm_gib")
    if v is not None:
        extras += f"; peak_hbm_gib={v}"
    shape_tag = ("higgs1m-synth" if args.rows == 1_000_000
                 else f"higgs{args.rows // 1_000_000}m-synth"
                 if args.rows % 1_000_000 == 0
                 else f"higgs{args.rows}-synth")
    base = CPU_LIGHTGBM_BASELINE.get(
        (args.goss, args.rows),
        (2.0 if args.goss else 1.0) * 1e6 / max(args.rows, 1))
    result = {
        "metric": ("boosting_iters_per_sec "
                   f"({shape_tag} nl={NUM_LEAVES} mb={MAX_BIN}; "
                   f"holdout_auc="
                   f"{_snap_gauge(snap, 'bench.holdout_auc'):.4f}"
                   f"@{args.warmup + args.iters}rounds; construct_s="
                   f"{_snap_gauge(snap, 'bench.construct_s'):.1f}; "
                   f"engine_init_s="
                   f"{_snap_gauge(snap, 'bench.engine_init_s'):.1f}; "
                   f"ttfi_s={_snap_gauge(snap, 'bench.ttfi_s'):.1f}"
                   f"{extras})"),
        "value": round(ips, 4),
        "unit": "iters/sec",
        "vs_baseline": round(ips / base, 4),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
