"""Benchmark: boosting iters/sec on synthetic Higgs-1M-like data.

Driver contract: print ONE JSON line
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}``.

Config mirrors BASELINE.json's flagship: binary classification, 28 dense
features, num_leaves=127, max_bin=255. The dataset is synthesized (no
network in this environment; Higgs itself is a download) at 1M rows —
matching the "Higgs-1M CPU hist baseline" config shape.

vs_baseline: BASELINE.md holds NO verified reference numbers (empty
mount). We compare against 1.0 iters/sec — the ballpark of CPU
hist-LightGBM on Higgs-1M-class data per BASELINE.md's unverified
recollection table — so vs_baseline > 1 means faster than CPU LightGBM.
"""
import json
import sys
import time

import numpy as np

N_ROWS = int(1e6)
N_HOLDOUT = 100_000
N_FEATURES = 28
NUM_LEAVES = 127
MAX_BIN = 255
WARMUP_ITERS = 40     # one full fused chunk (tpu_fuse_iters default)
BENCH_ITERS = 40
CPU_LIGHTGBM_BASELINE_ITERS_PER_SEC = 1.0  # UNVERIFIED, see BASELINE.md


def synth_higgs(n, f, seed=0):
    """Higgs-like: mixture of informative kinematic-ish features."""
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    w = rng.normal(size=f)
    logit = (X @ w * 0.5 + 0.8 * X[:, 0] * X[:, 1]
             + 0.5 * np.abs(X[:, 2]) - 0.4)
    y = (logit + rng.normal(scale=1.0, size=n) > 0).astype(np.float64)
    return X.astype(np.float64), y


def main():
    import lightgbm_tpu as lgb
    from lightgbm_tpu.boosting.gbdt import GBDT
    from lightgbm_tpu.config import Config

    X, y = synth_higgs(N_ROWS + N_HOLDOUT, N_FEATURES)
    X, X_ho = X[:N_ROWS], X[N_ROWS:]
    y, y_ho = y[:N_ROWS], y[N_ROWS:]
    t_bin = time.time()
    ds = lgb.Dataset(X, label=y)
    cfg = Config({"objective": "binary", "num_leaves": NUM_LEAVES,
                  "max_bin": MAX_BIN, "learning_rate": 0.1,
                  "verbosity": -1})
    eng = GBDT(cfg, ds)
    bin_time = time.time() - t_bin

    # warmup (jit compile + cache); same chunk length as the timed run so
    # the fused scan is compiled exactly once
    eng.train_chunk(WARMUP_ITERS)
    import jax
    jax.block_until_ready(eng.score)

    t0 = time.time()
    eng.train_chunk(BENCH_ITERS)
    jax.block_until_ready(eng.score)
    dt = time.time() - t0
    iters_per_sec = BENCH_ITERS / dt

    # held-out AUC as the quality guard (train-AUC would reward overfit)
    from lightgbm_tpu.metric import AUCMetric
    pred = eng.predict(X_ho)
    auc = AUCMetric(cfg).eval(pred, y_ho, None)[0][1]

    result = {
        "metric": ("boosting_iters_per_sec "
                   f"(higgs1m-synth nl={NUM_LEAVES} mb={MAX_BIN}; "
                   f"holdout_auc={auc:.4f}; binning_s={bin_time:.1f})"),
        "value": round(iters_per_sec, 4),
        "unit": "iters/sec",
        "vs_baseline": round(
            iters_per_sec / CPU_LIGHTGBM_BASELINE_ITERS_PER_SEC, 4),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
