"""Repo-native static analysis: six drift linters + allowlists.

``python -m tools.analyze`` — dependency-free (stdlib ``ast``), < 10 s,
wired into scripts/check.sh (``lint_findings=`` on the obs line, exit
code 6) and enforced absolutely by scripts/obs_trend.py. Catalogue,
allowlist workflow and how-to-add-a-checker: docs/static-analysis.md.

Checkers (each with ``tools/analyze/allowlists/<name>.txt``):

- ``capability-gate``      — eligibility literals outside capabilities.py
- ``config-knobs``         — raw/undeclared/undocumented ``tpu_*`` knobs
- ``obs-names``            — code ⟂ docs/observability.md catalogue drift
- ``collective-safety``    — collectives inside lax.switch/cond branches
                             or rank-divergent conditionals (PR 12 class)
- ``lock-discipline``      — obs shared state mutated outside the lock
- ``donation-discipline``  — a donated jit argument read again before
                             reassignment (use-after-donate, PR 16 class)
"""
from __future__ import annotations

import argparse
import os
import sys
import time
from typing import Dict, List, Optional

from . import (capability_gate, collective_safety, config_knobs,
               donation_discipline, lock_discipline, obs_names)
from .core import Allowlist, Finding, SourceSet, discover_sources

CHECKERS = {
    capability_gate.NAME: capability_gate.check,
    config_knobs.NAME: config_knobs.check,
    obs_names.NAME: obs_names.check,
    collective_safety.NAME: collective_safety.check,
    lock_discipline.NAME: lock_discipline.check,
    donation_discipline.NAME: donation_discipline.check,
}

REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def run(root: Optional[str] = None,
        checkers: Optional[List[str]] = None,
        use_allowlists: bool = True) -> List[Finding]:
    """All post-allowlist findings (plus allowlist-hygiene findings)."""
    root = root or REPO_ROOT
    sources = SourceSet(root, discover_sources(root))
    findings: List[Finding] = []
    for rel, err in sources.parse_errors:
        findings.append(Finding("parse", rel, 0, "syntax-error",
                                f"cannot parse: {err}"))
    for name in (checkers or sorted(CHECKERS)):
        raw = CHECKERS[name](sources)
        if use_allowlists:
            al = Allowlist.load(name)
            findings.extend(al.filter(raw))
            findings.extend(al.hygiene_findings())
        else:
            findings.extend(raw)
    return findings


def run_checker_on_source(name: str, source: str,
                          rel: str = "lightgbm_tpu/_fixture.py",
                          root: Optional[str] = None) -> List[Finding]:
    """Run ONE checker over an in-memory snippet (the fixture tests'
    entry point). The snippet is parsed under ``rel`` so path-scoped
    checkers (lock-discipline's obs/ scope) can be exercised; the real
    config.py rides along so config-knobs checks the snippet against
    the REAL declaration table; no allowlist is applied. Findings are
    returned for the snippet only."""
    import ast as _ast
    root = root or REPO_ROOT
    base = [config_knobs.CONFIG_FILE] if os.path.exists(
        os.path.join(root, config_knobs.CONFIG_FILE)) else []
    sources = SourceSet(root, base)
    sources.trees[rel] = _ast.parse(source)
    sources.texts[rel] = source
    return [f for f in CHECKERS[name](sources) if f.file == rel]


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.analyze",
        description="repo-native drift linters (docs/static-analysis.md)")
    ap.add_argument("--root", default=REPO_ROOT)
    ap.add_argument("--checker", action="append",
                    help="run only this checker (repeatable)")
    ap.add_argument("--no-allowlists", action="store_true",
                    help="show findings the allowlists would suppress")
    ap.add_argument("--emit-count", metavar="FILE",
                    help="write the finding count to FILE regardless "
                         "of exit status (scripts/check.sh reads it)")
    args = ap.parse_args(argv)
    for c in (args.checker or []):
        if c not in CHECKERS:
            ap.error(f"unknown checker {c!r} (known: "
                     f"{', '.join(sorted(CHECKERS))})")
    t0 = time.monotonic()
    findings = run(args.root, args.checker,
                   use_allowlists=not args.no_allowlists)
    for f in findings:
        print(f.render())
    n = len(findings)
    if args.emit_count:
        with open(args.emit_count, "w") as fh:
            fh.write(f"{n}\n")
    print(f"tools.analyze: {n} finding(s) across "
          f"{len(args.checker or CHECKERS)} checker(s) "
          f"in {time.monotonic() - t0:.2f}s")
    return 1 if n else 0
