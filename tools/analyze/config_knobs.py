"""Checker 2: config-knob drift — every ``tpu_*`` knob is declared
once, read through the declared accessors, and documented.

Three rules:

- **raw-read** — ``<dict>.get("tpu_...")`` anywhere outside config.py
  re-encodes the knob's default and coercion inline (the 15 raw reads
  in parallel/launch.py and io/dataset.py each carried their own copy
  of the default before PR 14). Sanctioned reads: a resolved ``Config``
  attribute, ``getattr(cfg, "tpu_...")``, or
  :func:`lightgbm_tpu.config.get_param` for dict-shaped params.
- **undeclared** — a ``tpu_*`` name read via ``get_param``/``getattr``/
  ``.get`` (or written via ``params["tpu_..."] = ...``) that is not a
  ``_PARAMS`` key in config.py: a typo'd or never-registered knob
  silently does nothing.
- **undocumented** — a declared ``tpu_*`` knob that appears in neither
  README.md nor any docs/*.md: users cannot discover it.

Keys: ``raw-read:<knob>``, ``undeclared:<name>``,
``undocumented:<knob>``.
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Set, Tuple

from .core import Finding, SourceSet, call_name, const_str

NAME = "config-knobs"

CONFIG_FILE = "lightgbm_tpu/config.py"
_KNOB_RE = re.compile(r"^tpu_[a-z0-9_]+$")


def declared_knobs(sources: SourceSet) -> Set[str]:
    """_PARAMS keys from config.py's AST (all of them; the doc rule
    filters to tpu_*)."""
    tree = sources.trees.get(CONFIG_FILE)
    if tree is None:
        return set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            is_params = any(isinstance(t, ast.Name) and t.id == "_PARAMS"
                            for t in node.targets)
        elif isinstance(node, ast.AnnAssign):
            is_params = (isinstance(node.target, ast.Name)
                         and node.target.id == "_PARAMS")
        else:
            continue
        if is_params and isinstance(node.value, ast.Dict):
            return {k.value for k in node.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)}
    return set()


def _doc_text(root: str) -> str:
    chunks = []
    for rel in ["README.md"] + sorted(
            os.path.join("docs", f)
            for f in (os.listdir(os.path.join(root, "docs"))
                      if os.path.isdir(os.path.join(root, "docs"))
                      else [])
            if f.endswith(".md")):
        path = os.path.join(root, rel)
        if os.path.exists(path):
            chunks.append(open(path, encoding="utf-8").read())
    return "\n".join(chunks)


def _knob_reads(tree: ast.Module) -> List[Tuple[str, int, str]]:
    """(knob, line, kind) for every tpu_* read/write in one module.
    kind: "dict-get" (the banned shape), "accessor" (get_param /
    getattr / subscript-store — fine, but must name a declared knob)."""
    out: List[Tuple[str, int, str]] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fn = call_name(node)
            if fn == "get" and isinstance(node.func, ast.Attribute) \
                    and node.args:
                s = const_str(node.args[0])
                if s and _KNOB_RE.match(s):
                    out.append((s, node.lineno, "dict-get"))
            elif fn in ("get_param", "getattr") and len(node.args) >= 2:
                s = const_str(node.args[1])
                if s and _KNOB_RE.match(s):
                    out.append((s, node.lineno, "accessor"))
        elif isinstance(node, ast.Subscript):
            s = const_str(node.slice)
            if s and _KNOB_RE.match(s):
                out.append((s, node.lineno, "accessor"))
        elif isinstance(node, ast.Attribute):
            if _KNOB_RE.match(node.attr):
                out.append((node.attr, node.lineno, "accessor"))
    return out


def check(sources: SourceSet) -> List[Finding]:
    declared = declared_knobs(sources)
    docs = _doc_text(sources.root)
    out: List[Finding] = []
    seen_undeclared: Set[Tuple[str, str]] = set()
    for rel, tree in sources.items():
        if rel == CONFIG_FILE:
            continue
        for knob, line, kind in _knob_reads(tree):
            if kind == "dict-get":
                out.append(Finding(
                    NAME, rel, line, f"raw-read:{knob}",
                    f'raw params.get("{knob}") — route through '
                    f"Config / config.get_param so the declared "
                    f"default, aliasing and coercion apply "
                    f"(docs/static-analysis.md)"))
            if knob not in declared and (rel, knob) not in seen_undeclared:
                seen_undeclared.add((rel, knob))
                out.append(Finding(
                    NAME, rel, line, f"undeclared:{knob}",
                    f'"{knob}" is not declared in config.py _PARAMS '
                    f"— a typo'd or unregistered knob silently does "
                    f"nothing"))
    tpu_declared = sorted(k for k in declared if k.startswith("tpu_"))
    for knob in tpu_declared:
        if knob not in docs:
            out.append(Finding(
                NAME, CONFIG_FILE, 0, f"undocumented:{knob}",
                f'declared knob "{knob}" appears in neither README.md '
                f"nor docs/*.md — document it where its subsystem "
                f"lives"))
    return out
