"""Checker 3: obs-name drift — code and docs/observability.md agree on
the metric/span/heartbeat name catalogue, in BOTH directions.

- **undocumented** — a name emitted in code (first constant-string arg
  of ``obs.inc`` / ``obs.observe`` / ``obs.set_gauge`` / ``obs.span`` /
  ``registry().counter|gauge|histogram``) that the catalogue does not
  list: dashboards cannot discover it.
- **unemitted** — a catalogued name no code emits: the doc describes a
  signal that does not exist (the rot direction PR 13's review caught
  by hand).

Docs side: backticked tokens in docs/observability.md shaped like a
metric name (lowercase dotted/slashed path). ``bench.*``-style entries
are prefix wildcards. ``{label=...}`` suffixes are stripped. Tokens
that are obviously API/file references (``obs.enable``, ``*.py``) are
ignored. Code side: names built dynamically (f-strings, dict-driven
gauges) are invisible to the AST — catalogue entries for those go in
the allowlist with the reason naming the emitting site.

Keys: ``undocumented:<name>``, ``unemitted:<name>``.
"""
from __future__ import annotations

import ast
import os
import re
from typing import List, Set, Tuple

from .core import Finding, SourceSet, call_name, const_str

NAME = "obs-names"

DOC_FILE = os.path.join("docs", "observability.md")

EMIT_FUNCS = ("inc", "observe", "set_gauge", "span", "counter",
              "gauge", "histogram")

# a metric/span name: lowercase segments joined by '.' or '/'
_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*([./][a-z0-9_]+)+$")
_WILD_RE = re.compile(r"^[a-z][a-z0-9_]*\.\*$")
_TICK_RE = re.compile(r"`([^`]+)`")
# backticked tokens that are python-API / file references, not metric
# names: module attribute paths and anything with a file extension
_API_PREFIXES = ("obs.", "lgb.", "jax.", "np.", "numpy.",
                 "lightgbm_tpu.", "self.", "config.", "sys.", "os.")
_FILE_SUFFIXES = (".py", ".md", ".sh", ".json", ".jsonl", ".log",
                  ".cpp", ".hpp", ".h", ".rst", ".csv", ".txt",
                  ".conf", ".dev")


def emitted_names(sources: SourceSet) -> Set[Tuple[str, str, int]]:
    """(name, file, line) for every constant-name emission call."""
    out = set()
    for rel, tree in sources.items():
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if call_name(node) not in EMIT_FUNCS or not node.args:
                continue
            s = const_str(node.args[0])
            if s and _NAME_RE.match(s):
                out.add((s, rel, node.lineno))
    return out


def mentioned_names(sources: SourceSet) -> Set[str]:
    """Every constant string ANYWHERE in code shaped like a metric
    name — the loose set the docs→code direction checks against (it
    catches names that reach the registry through dicts/tuples, e.g.
    the slo.* gauges derived in SloTracker.compute)."""
    out = set()
    for _rel, tree in sources.items():
        for node in ast.walk(tree):
            s = const_str(node)
            if s and _NAME_RE.match(s):
                out.add(s)
    return out


def documented_names(root: str) -> Tuple[Set[str], Set[str]]:
    """(exact names, wildcard prefixes) from the doc catalogue."""
    path = os.path.join(root, DOC_FILE)
    if not os.path.exists(path):
        return set(), set()
    text = open(path, encoding="utf-8").read()
    exact: Set[str] = set()
    wild: Set[str] = set()
    for tok in _TICK_RE.findall(text):
        tok = tok.strip()
        # strip a {label=...} suffix: slo.breached{slo=...} -> slo.breached
        tok = re.sub(r"\{[^}]*\}$", "", tok)
        if ("(" in tok or " " in tok or "=" in tok
                or tok.startswith(_API_PREFIXES)
                or tok.endswith(_FILE_SUFFIXES)):
            continue
        if _WILD_RE.match(tok):
            wild.add(tok[:-2])
        elif _NAME_RE.match(tok):
            exact.add(tok)
    return exact, wild


def _covered(name: str, exact: Set[str], wild: Set[str]) -> bool:
    return name in exact or any(name == w or name.startswith(w + ".")
                                for w in wild)


def check(sources: SourceSet) -> List[Finding]:
    exact, wild = documented_names(sources.root)
    out: List[Finding] = []
    emitted = emitted_names(sources)
    emitted_set = {n for n, _f, _l in emitted}
    reported: Set[str] = set()
    for name, rel, line in sorted(emitted):
        if not _covered(name, exact, wild) and name not in reported:
            reported.add(name)
            out.append(Finding(
                NAME, rel, line, f"undocumented:{name}",
                f"metric/span `{name}` is emitted here but missing "
                f"from the docs/observability.md catalogue"))
    mentioned = mentioned_names(sources) | emitted_set
    for name in sorted(exact):
        if name not in mentioned:
            out.append(Finding(
                NAME, DOC_FILE, 0, f"unemitted:{name}",
                f"docs/observability.md catalogues `{name}` but no "
                f"code emits (or even mentions) it — fix the doc or "
                f"the emission"))
    return out
