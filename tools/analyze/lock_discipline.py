"""Checker 5: lock-discipline — the obs registry and SLO tracker are
scraped from server threads while training/serving threads write them;
shared state mutated outside ``with self._lock`` is a data race.

Scope: classes under ``lightgbm_tpu/obs/`` that create a
``self._lock`` in ``__init__`` (MetricsRegistry, the metric types, the
time-ring SLIs, SloTracker). *Shared state* is every ``self.<attr>``
assigned in ``__init__`` (own or same-module ancestor). A mutation —
assign / augassign / ``del`` / a mutating method call
(``.append``/``.add``/``.clear``/...) on such an attribute, or through
a subscript of it — outside a lexical ``with self._lock`` block and
outside ``__init__`` is a finding.

Exemption convention (repo-native, already used by ``_TimeRing``):
a method whose docstring says "caller holds the lock" declares itself
a lock-held helper — callers take the lock, the checker trusts the
declaration (and a reviewer can grep the phrase). Anything else
intentional goes in the allowlist with a reason.

Key: ``<Class>.<method>:<attr>``.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set

from .core import Finding, SourceSet

NAME = "lock-discipline"

SCOPE_PREFIX = "lightgbm_tpu/obs/"
LOCK_ATTR = "_lock"
_HELD_RE = re.compile(r"caller holds the lock", re.IGNORECASE)
MUTATORS = {"append", "add", "clear", "pop", "popitem", "update",
            "extend", "remove", "insert", "discard", "setdefault"}


def _init_attrs(cls: ast.ClassDef) -> Set[str]:
    """self.<attr> names assigned anywhere in this class's __init__."""
    out: Set[str] = set()
    for item in cls.body:
        if isinstance(item, ast.FunctionDef) and item.name == "__init__":
            for n in ast.walk(item):
                if (isinstance(n, ast.Attribute)
                        and isinstance(n.ctx, ast.Store)
                        and isinstance(n.value, ast.Name)
                        and n.value.id == "self"):
                    out.add(n.attr)
    return out


def _self_attr_of(node: ast.AST) -> Optional[str]:
    """self.<attr> at the ROOT of a (possibly subscripted) target."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _is_lock_with(item: ast.withitem) -> bool:
    ctx = item.context_expr
    return (isinstance(ctx, ast.Attribute) and ctx.attr == LOCK_ATTR
            and isinstance(ctx.value, ast.Name)
            and ctx.value.id == "self")


def _mutations(node: ast.AST, shared: Set[str], under_lock: bool,
               hits: List):
    """Recursive walk tracking `with self._lock` lexical scope."""
    if isinstance(node, ast.With):
        locked = under_lock or any(_is_lock_with(i) for i in node.items)
        for child in node.body:
            _mutations(child, shared, locked, hits)
        return
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.Lambda)):
        return      # nested callables are their own discipline problem
    attr = None
    if isinstance(node, (ast.Assign, ast.AugAssign)):
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        for t in targets:
            a = _self_attr_of(t)
            if a and a in shared and a != LOCK_ATTR and not under_lock:
                hits.append((node.lineno, a))
    elif isinstance(node, ast.Delete):
        for t in node.targets:
            a = _self_attr_of(t)
            if a and a in shared and not under_lock:
                hits.append((node.lineno, a))
    elif isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in MUTATORS:
            a = _self_attr_of(f.value)
            if a and a in shared and not under_lock:
                hits.append((node.lineno, a))
    for child in ast.iter_child_nodes(node):
        _mutations(child, shared, under_lock, hits)
    return attr


def check(sources: SourceSet) -> List[Finding]:
    out: List[Finding] = []
    for rel, tree in sources.items():
        if not rel.startswith(SCOPE_PREFIX):
            continue
        classes: Dict[str, ast.ClassDef] = {
            n.name: n for n in ast.walk(tree)
            if isinstance(n, ast.ClassDef)}
        # same-module inheritance: attrs + the lock may come from a base
        attrs_of: Dict[str, Set[str]] = {}

        def resolved_attrs(name: str, seen=()) -> Set[str]:
            if name in attrs_of:
                return attrs_of[name]
            cls = classes.get(name)
            if cls is None or name in seen:
                return set()
            s = _init_attrs(cls)
            for b in cls.bases:
                if isinstance(b, ast.Name):
                    s |= resolved_attrs(b.id, seen + (name,))
            attrs_of[name] = s
            return s

        for cname, cls in classes.items():
            shared = resolved_attrs(cname)
            if LOCK_ATTR not in shared:
                continue
            for item in cls.body:
                if not isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                if item.name == "__init__":
                    continue
                doc = ast.get_docstring(item) or ""
                if _HELD_RE.search(doc):
                    continue    # declared lock-held helper
                hits: List = []
                for stmt in item.body:
                    _mutations(stmt, shared, False, hits)
                for line, attr in hits:
                    out.append(Finding(
                        NAME, rel, line,
                        f"{cname}.{item.name}:{attr}",
                        f"`self.{attr}` mutated in {cname}."
                        f"{item.name} outside `with self._lock` — "
                        f"scrape threads race this state; take the "
                        f"lock or declare the method "
                        f'"caller holds the lock"'))
    return out
