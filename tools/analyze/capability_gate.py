"""Checker 1: capability-gate lint — eligibility literals belong in
lightgbm_tpu/capabilities.py.

The drift class this kills: PRs 5/10/12 each fixed a bug where one
routing site's inline list (``config.objective in ("binary", ...)``,
``tree_learner in ("serial", "data")``) fell out of sync with another
site's copy. After the PR-14 refactor every such judgment reads the ONE
capability table, so ANY membership test of a dispatch attribute
(:data:`GATE_ATTRS`) against a literal string container outside
capabilities.py is a regression.

Flagged shape::

    <expr>.objective in ("binary", "regression")      # and not-in
    config.tree_learner not in ("serial", "data")

Key: ``<attr>@<enclosing-qualname>``.
"""
from __future__ import annotations

import ast
from typing import List

from .core import Finding, SourceSet, attr_chain

NAME = "capability-gate"

# config attributes whose value space routes between engines/learners:
# an inline literal membership test over one of these IS an eligibility
# list (the thing the capability table centralizes)
GATE_ATTRS = ("objective", "boosting", "tree_learner",
              "data_sample_strategy")

# the table itself (and its tests) legitimately hold the literals
EXEMPT_FILES = ("lightgbm_tpu/capabilities.py",)


def _gate_attr(node: ast.AST) -> str:
    """The GATE_ATTRS name this expression reads, "" otherwise.
    Unwraps str()/getattr-style wrappers: ``str(config.objective)``."""
    if isinstance(node, ast.Call) and node.args:
        # str(config.objective), some_fn(config.boosting)
        return _gate_attr(node.args[0])
    chain = attr_chain(node)
    if not chain:
        return ""
    leaf = chain.rsplit(".", 1)[-1]
    return leaf if leaf in GATE_ATTRS else ""


def _is_str_container(node: ast.AST) -> bool:
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return bool(node.elts) and all(
            isinstance(e, ast.Constant) and isinstance(e.value, str)
            for e in node.elts)
    return False


class _Visitor(ast.NodeVisitor):
    def __init__(self, rel: str):
        self.rel = rel
        self.scope: List[str] = ["<module>"]
        self.findings: List[Finding] = []

    def _visit_scope(self, node):
        self.scope.append(node.name)
        self.generic_visit(node)
        self.scope.pop()

    visit_FunctionDef = _visit_scope
    visit_AsyncFunctionDef = _visit_scope
    visit_ClassDef = _visit_scope

    def visit_Compare(self, node: ast.Compare):
        for op, comparator in zip(node.ops, node.comparators):
            if not isinstance(op, (ast.In, ast.NotIn)):
                continue
            if not _is_str_container(comparator):
                continue
            attr = _gate_attr(node.left)
            if attr:
                qual = self.scope[-1]
                self.findings.append(Finding(
                    NAME, self.rel, node.lineno, f"{attr}@{qual}",
                    f"inline eligibility literal: `{attr}` tested "
                    f"against a literal container in `{qual}` — move "
                    f"the list into lightgbm_tpu/capabilities.py and "
                    f"test against the named constant"))
        self.generic_visit(node)


def check(sources: SourceSet) -> List[Finding]:
    out: List[Finding] = []
    for rel, tree in sources.items():
        if rel in EXEMPT_FILES:
            continue
        v = _Visitor(rel)
        v.visit(tree)
        out.extend(v.findings)
    return out
