"""Checker 4: collective-safety — the PR 12 deadlock class.

Invariant (docs/perf.md "Streamed × sharded"; learner/serial.py's span
switch): cross-device collectives (``psum`` / ``psum_scatter`` /
``all_gather`` / the shared ``hist_allreduce`` wire) must stay OUTSIDE

- ``lax.switch`` / ``lax.cond`` branch functions: under SPMD a branch
  index that is not provably uniform across ranks lets different ranks
  enter different branches, and a collective inside one branch then
  waits forever for peers executing the other — the deadlock PR 12
  debugged. 71 collective-reachable call sites across 10 files were
  previously guarded only by reviewer memory.
- rank-divergent Python conditionals: ``if process_index() == 0: ...``
  (or an ``if`` over a ``rank``-named value) around a collective
  diverges the gang at trace time.
- background-thread dispatch: a callable handed to
  ``executor.submit(...)``, ``Thread(target=...)`` or a
  ``BlockPrefetcher`` staging slot (utils/prefetch.py) runs off the
  main thread — if it reaches a collective, per-rank collective launch
  order becomes a thread-scheduling accident and the gang deadlocks
  exactly like the branch case. The ``tpu_stream_overlap`` pipeline's
  staging contract ("slice/pad/device_put only, never a collective")
  is this rule, enforced statically.

Detection: per module, a call graph over locally-defined functions
(including nested defs and lambdas) is fixpointed into the set of
*collective-reaching* functions. A reference to such a function in a
``lax.switch``/``lax.cond`` branch position — directly, or through a
local ``branches``-list variable (``branches.append(f)`` /
``branches = [f, g]``) — is a finding, as is a collective-reaching
call lexically inside a rank-divergent ``if``.

The ONE intentional exception (the packed-wire fallback in
learner/collective.py, whose cond predicate is itself a psum output and
therefore mesh-uniform by construction) lives in the allowlist with
that reasoning spelled out.

Keys: ``branch:<function>@<switch-site-function>``,
``rank-if:<collective>@<enclosing-function>``,
``thread:<function>@<dispatch-site-function>``.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .core import Finding, SourceSet, call_name

NAME = "collective-safety"

COLLECTIVES = {"psum", "psum_scatter", "all_gather", "pmean",
               "all_to_all", "hist_allreduce"}
RANK_NAMES = {"process_index", "axis_index", "rank", "local_rank",
              "proc_index"}


class _FnInfo:
    def __init__(self, qual: str, node: ast.AST):
        self.qual = qual
        self.node = node
        self.calls: Set[str] = set()        # local function names called
        self.collective: bool = False       # directly calls a collective
        self.nested: Set[str] = set()       # defs nested inside (any depth)


def _walk_pruned(node: ast.AST):
    """ast.walk over one function's OWN body — does not descend into
    nested function definitions (their calls are their own)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _collect_functions(tree: ast.Module) -> Dict[str, _FnInfo]:
    """name -> info for every def; nested defs register under their
    bare name AND their dotted qualname (branch references use the
    bare name)."""
    fns: Dict[str, _FnInfo] = {}

    def walk_fn(node, qual: str) -> _FnInfo:
        info = _FnInfo(qual, node)
        for n in _walk_pruned(node):
            if isinstance(n, ast.Call):
                cn = call_name(n)
                if cn in COLLECTIVES:
                    info.collective = True
                elif cn:
                    info.calls.add(cn)
        for n in ast.walk(node):
            if n is not node and isinstance(
                    n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info.nested.add(n.name)
        return info

    def visit(node, prefix: str):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                info = walk_fn(child, qual)
                # nested defs shadow same-named outer ones per scope;
                # over-approximate by keeping the first registration
                fns.setdefault(child.name, info)
                fns.setdefault(qual, info)
                visit(child, qual + ".")
            else:
                visit(child, prefix)

    visit(tree, "")
    return fns


def _fixpoint(fns: Dict[str, _FnInfo]) -> Set[str]:
    """Names of collective-reaching functions (direct or via local
    calls)."""
    reaching = {n for n, i in fns.items() if i.collective}
    changed = True
    while changed:
        changed = False
        for n, i in fns.items():
            if n not in reaching and (i.calls & reaching):
                reaching.add(n)
                changed = True
    return reaching


def _branch_refs(arg: ast.AST,
                 list_vars: Dict[str, Set[str]]) -> Set[str]:
    """Function names referenced by one switch/cond branch operand."""
    out: Set[str] = set()
    if isinstance(arg, ast.Name):
        out.add(arg.id)
        out |= list_vars.get(arg.id, set())
    elif isinstance(arg, (ast.Tuple, ast.List)):
        for e in arg.elts:
            out |= _branch_refs(e, list_vars)
    elif isinstance(arg, ast.Lambda):
        for n in ast.walk(arg.body):
            if isinstance(n, ast.Call):
                cn = call_name(n)
                if cn:
                    out.add(cn)
                if cn in COLLECTIVES:
                    out.add(cn)
    return out


def _thread_target_refs(arg: ast.AST) -> Set[str]:
    """Function names referenced by one async-dispatch operand: the
    first arg of ``submit``, the ``target=`` of ``Thread``, the stage
    callable of ``BlockPrefetcher``. Bound methods reference by their
    attr name (``self._stage_bins`` -> ``_stage_bins``) — module-local
    defs register under bare names, so this matches the call graph."""
    out: Set[str] = set()
    if isinstance(arg, ast.Name):
        out.add(arg.id)
    elif isinstance(arg, ast.Attribute):
        out.add(arg.attr)
    elif isinstance(arg, ast.Lambda):
        for n in ast.walk(arg.body):
            if isinstance(n, ast.Call):
                cn = call_name(n)
                if cn:
                    out.add(cn)
    return out


def _rank_divergent(test: ast.AST) -> Optional[str]:
    """Name evidence that an `if` test reads a rank identity."""
    for n in ast.walk(test):
        name = ""
        if isinstance(n, ast.Call):
            name = call_name(n)
        elif isinstance(n, ast.Name):
            name = n.id
        elif isinstance(n, ast.Attribute):
            name = n.attr
        if name in RANK_NAMES:
            return name
    return None


class _ModuleChecker(ast.NodeVisitor):
    def __init__(self, rel: str, fns: Dict[str, _FnInfo],
                 reaching: Set[str]):
        self.rel = rel
        self.fns = fns
        self.reaching = reaching
        self.scope: List[str] = ["<module>"]
        # per enclosing-function map of list-var -> appended fn names
        self.list_vars: Dict[str, Set[str]] = {}
        self.findings: List[Finding] = []

    def _enter(self, node):
        self.scope.append(node.name)
        saved = self.list_vars
        self.list_vars = dict(saved)
        self.generic_visit(node)
        self.list_vars = saved
        self.scope.pop()

    visit_FunctionDef = _enter
    visit_AsyncFunctionDef = _enter

    def visit_Assign(self, node: ast.Assign):
        # branches = [f, g, ...]
        if (len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, (ast.List, ast.Tuple))):
            refs = {e.id for e in node.value.elts
                    if isinstance(e, ast.Name)}
            if refs:
                self.list_vars[node.targets[0].id] = refs
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        fn_name = call_name(node)
        # branches.append(f) / branches.append(mk(x))
        if (fn_name == "append" and isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.args):
            var = node.func.value.id
            refs = self.list_vars.setdefault(var, set())
            a = node.args[0]
            if isinstance(a, ast.Name):
                refs.add(a.id)
            elif isinstance(a, ast.Call):
                # factory pattern: append(mk(S)) — the factory's
                # RETURNED closure is what runs; over-approximate with
                # the factory's own collective reach (its nested defs
                # register under their bare names)
                cn = call_name(a)
                if cn:
                    refs.add(cn)
        # async dispatch: executor.submit(fn, ...) / Thread(target=fn)
        # / BlockPrefetcher(stage, ...) — the handed callable runs on a
        # background thread; reaching a collective there makes per-rank
        # launch order a scheduling accident (gang deadlock)
        dispatch_ops: List[ast.AST] = []
        if fn_name == "submit" and node.args:
            dispatch_ops.append(node.args[0])
        elif fn_name == "Thread":
            dispatch_ops.extend(kw.value for kw in node.keywords
                                if kw.arg == "target")
        elif fn_name == "BlockPrefetcher":
            if node.args:
                dispatch_ops.append(node.args[0])
            dispatch_ops.extend(kw.value for kw in node.keywords
                                if kw.arg == "stage")
        for op in dispatch_ops:
            for ref in sorted(_thread_target_refs(op)):
                expanded = {ref} | (self.fns[ref].nested
                                    if ref in self.fns else set())
                if any(r in self.reaching or r in COLLECTIVES
                       for r in expanded):
                    self.findings.append(Finding(
                        NAME, self.rel, node.lineno,
                        f"thread:{ref}@{self.scope[-1]}",
                        f"collective-reaching function `{ref}` is "
                        f"dispatched to a background thread "
                        f"(`{fn_name}`) in `{self.scope[-1]}` — "
                        f"per-rank collective launch order becomes a "
                        f"thread-scheduling accident and the gang "
                        f"deadlocks; collectives must dispatch "
                        f"gang-uniformly from the main thread "
                        f"(utils/prefetch.py staging contract)"))
        if fn_name in ("switch", "cond"):
            branch_args = node.args[1:]
            for arg in branch_args:
                for ref in sorted(_branch_refs(arg, self.list_vars)):
                    # a factory reference (branches.append(mk(S)))
                    # stands in for the closures defined inside it
                    expanded = {ref} | (self.fns[ref].nested
                                        if ref in self.fns else set())
                    if any(r in self.reaching or r in COLLECTIVES
                           for r in expanded):
                        self.findings.append(Finding(
                            NAME, self.rel, node.lineno,
                            f"branch:{ref}@{self.scope[-1]}",
                            f"collective-reaching function `{ref}` is "
                            f"a lax.{fn_name} branch in "
                            f"`{self.scope[-1]}` — a rank-divergent "
                            f"branch index deadlocks the gang "
                            f"(PR 12 class); hoist the collective "
                            f"out of the branch"))
        self.generic_visit(node)

    def visit_If(self, node: ast.If):
        ev = _rank_divergent(node.test)
        if ev:
            # BOTH suites: `if rank == 0: log() else: psum(...)` is
            # just as divergent as the collective sitting in the body
            # (elif chains are Ifs nested in orelse and are covered)
            for part in node.body + node.orelse:
                for n in ast.walk(part):
                    if isinstance(n, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                        continue
                    if isinstance(n, ast.Call):
                        cn = call_name(n)
                        if cn in COLLECTIVES or cn in self.reaching:
                            self.findings.append(Finding(
                                NAME, self.rel, n.lineno,
                                f"rank-if:{cn}@{self.scope[-1]}",
                                f"collective `{cn}` inside an "
                                f"`if {ev} ...` block in "
                                f"`{self.scope[-1]}` — ranks diverge "
                                f"and the collective waits forever"))
        self.generic_visit(node)


def check(sources: SourceSet) -> List[Finding]:
    out: List[Finding] = []
    for rel, tree in sources.items():
        fns = _collect_functions(tree)
        reaching = _fixpoint(fns)
        mc = _ModuleChecker(rel, fns, reaching)
        mc.visit(tree)
        out.extend(mc.findings)
    return out
