"""Checker 6: donation-discipline — a ``jax.jit(donate_argnums=...)``
deletes its donated argument buffers at DISPATCH, so a call site that
reads the same Python reference again before reassigning it holds a
latent ``RuntimeError: Array has been deleted`` that detonates far from
the donating call (docs/perf.md "Iteration floor"; the runtime twin is
``utils/debug.py::donation_guard`` under ``tpu_debug_checks``).

What is tracked, lexically (stdlib ``ast``, one pass per module):

- *donors*: a local name (or ``self.<attr>``) bound to a call whose
  subtree contains ``jit(..., donate_argnums=...)`` — wrapper calls
  around the jit (the repo's ``_guard(jax.jit(...), "site")`` pattern)
  are seen through. The donated POSITIONS are every int constant in
  the ``donate_argnums`` expression, resolving one level of local
  names (``_don = (4,) if x else (); jax.jit(f, donate_argnums=_don)``
  donates {4}): a conditionally-donating jit must satisfy the
  discipline of its donating arm.
- *call sites* of a donor in the same scope (donor bindings are
  visible to nested functions, like the closures in boosting/gbdt.py;
  ``self.<attr>`` donors are hoisted to the class scope by a pre-pass,
  so an ``__init__``-built jit called from a sibling method is checked
  whatever the method order).
  For each donated position whose argument is a bare name or a
  ``self.<attr>``, a finding fires when that reference is READ again
  before being reassigned:

  1. in any later statement of the enclosing body (loads are checked
     before stores within a statement, so ``x = g(x)`` after ``f(x)``
     donated ``x`` is correctly a finding — the load feeds ``g``);
  2. by the NEXT ITERATION of an enclosing loop: a donating call
     inside a loop whose donated reference is never reassigned in that
     loop body re-reads the deleted buffer when the loop comes around
     (the carry must be rebound, ``score = step(score)``-style).

Reassignment kills tracking (plain store, tuple-unpack, ``for`` target,
``with ... as``); ``del`` also kills it (an explicit drop is the
opposite of a stale read). Nested function definitions are their own
scope — a closure that captures a donated name is a runtime-ordering
question this lexical pass stays out of.

Key: ``<scope>.<donor>:<ref>`` (scope = enclosing function name or
"<module>" — stable across line edits).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, SourceSet

NAME = "donation-discipline"

# a reference we can track: ("", name) for a bare local,
# ("self", attr) for self.<attr>
Ref = Tuple[str, str]


def _ref_of(node: ast.AST) -> Optional[Ref]:
    if isinstance(node, ast.Name):
        return ("", node.id)
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return ("self", node.attr)
    return None


def _fmt(ref: Ref) -> str:
    return f"self.{ref[1]}" if ref[0] == "self" else ref[1]


def _int_consts(node: ast.AST) -> Set[int]:
    """Every int constant in an expression — the donated positions of
    a donate_argnums value like ``((9,) if a else ()) + ((5,) if b
    else ())`` resolve to {9, 5} (bools are not argnums)."""
    out: Set[int] = set()
    for n in ast.walk(node):
        if (isinstance(n, ast.Constant) and isinstance(n.value, int)
                and not isinstance(n.value, bool)):
            out.add(n.value)
    return out


def _donated_positions(rhs: ast.AST,
                       local_exprs: Dict[str, ast.AST]) -> Set[int]:
    """Donated argnums of the innermost ``jit(...)`` call in ``rhs``
    (wrapper calls are seen through); {} when none donates."""
    for n in ast.walk(rhs):
        if not isinstance(n, ast.Call):
            continue
        f = n.func
        tail = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else "")
        if tail != "jit":
            continue
        for kw in n.keywords:
            if kw.arg != "donate_argnums":
                continue
            val = kw.value
            if isinstance(val, ast.Name) and val.id in local_exprs:
                val = local_exprs[val.id]
            return _int_consts(val)
    return set()


def _stores_in(node: ast.AST, ref: Ref) -> bool:
    """Does this subtree (nested defs included — any rebind in the loop
    body counts, wherever it lexically sits) store or ``del`` ref?"""
    for n in ast.walk(node):
        if isinstance(n, (ast.Name, ast.Attribute)):
            if (_ref_of(n) == ref
                    and isinstance(n.ctx, (ast.Store, ast.Del))):
                return True
    return False


def _first_read(node: ast.AST, ref: Ref) -> Optional[int]:
    """Line of a Load of ref in this subtree, None if absent. Skips
    nested function/lambda bodies (their execution time is unknown)."""
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.Lambda)):
        return None
    if (isinstance(node, (ast.Name, ast.Attribute))
            and _ref_of(node) == ref
            and isinstance(node.ctx, ast.Load)):
        return node.lineno
    for child in ast.iter_child_nodes(node):
        line = _first_read(child, ref)
        if line is not None:
            return line
    return None


def _read_before_store(stmts: List[ast.stmt],
                       ref: Ref) -> Optional[int]:
    """Scan statements in order: line of the first Load of ref before
    any Store kills the tracking (loads within a statement are checked
    first — RHS evaluates before the target binds)."""
    for stmt in stmts:
        line = _first_read(stmt, ref)
        if line is not None:
            return line
        if _stores_in(stmt, ref):
            return None
    return None


class _Scope:
    """One lexical scope's donor table, visible to nested scopes."""

    def __init__(self, name: str, parent: Optional["_Scope"] = None,
                 is_class: bool = False):
        self.name = name
        self.parent = parent
        self.is_class = is_class
        self.donors: Dict[Ref, Set[int]] = {}
        # last plain RHS per local name, for donate_argnums=NAME
        self.exprs: Dict[str, ast.AST] = {}

    def lookup(self, ref: Ref) -> Optional[Set[int]]:
        s: Optional[_Scope] = self
        while s is not None:
            if ref in s.donors:
                return s.donors[ref]
            s = s.parent
        return None

    def flat_exprs(self) -> Dict[str, ast.AST]:
        out: Dict[str, ast.AST] = {}
        s: Optional[_Scope] = self
        chain = []
        while s is not None:
            chain.append(s)
            s = s.parent
        for sc in reversed(chain):
            out.update(sc.exprs)
        return out


# compound statements whose BODIES are scanned by their own recursion
# step — only the header expressions belong to the statement itself
_HEADERS = {ast.For: ("target", "iter"),
            ast.AsyncFor: ("target", "iter"),
            ast.While: ("test",), ast.If: ("test",),
            ast.With: ("items",), ast.AsyncWith: ("items",),
            ast.Try: ()}


def _calls_in(stmt: ast.stmt) -> List[ast.Call]:
    """Calls lexically in this statement, skipping nested defs and the
    bodies of compound statements (those recurse separately with their
    own continuation)."""
    out: List[ast.Call] = []

    def walk(node: ast.AST):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return
        if type(node) in _HEADERS:
            for fname in _HEADERS[type(node)]:
                v = getattr(node, fname)
                for x in (v if isinstance(v, list) else [v]):
                    walk(x)
            return
        if isinstance(node, ast.Call):
            out.append(node)
        for child in ast.iter_child_nodes(node):
            walk(child)

    walk(stmt)
    return out


def _collect_class_donors(cls: ast.ClassDef, scope: _Scope) -> None:
    """Pre-pass over a class body: ``self.<attr> = ...jit(donate...)``
    in ANY method registers a class-scope donor, so a call site in a
    sibling method (the ``__init__``-builds / ``step``-calls split) is
    checked regardless of method order."""
    for item in cls.body:
        if not isinstance(item, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
            continue
        exprs: Dict[str, ast.AST] = {}
        for n in ast.walk(item):
            if not (isinstance(n, ast.Assign) and len(n.targets) == 1):
                continue
            ref = _ref_of(n.targets[0])
            if ref is None:
                continue
            if ref[0] == "":
                exprs[ref[1]] = n.value
                continue
            pos = _donated_positions(n.value, exprs)
            if pos:
                scope.donors[ref] = pos


def _scan_body(rel: str, body: List[ast.stmt], scope: _Scope,
               loop_bodies: List[List[ast.stmt]],
               rest: List[ast.stmt], out: List[Finding]) -> None:
    """``rest`` is the continuation: statements that run after this
    body completes (reads there see the donated buffer too)."""
    for i, stmt in enumerate(body):
        later = body[i + 1:] + rest
        # donor definitions: NAME = <expr containing jit(donate...)>
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            ref = _ref_of(stmt.targets[0])
            if ref is not None:
                pos = _donated_positions(stmt.value, scope.flat_exprs())
                if pos:
                    scope.donors[ref] = pos
                if ref[0] == "":
                    scope.exprs[ref[1]] = stmt.value
        # donor call sites in this statement
        for call in _calls_in(stmt):
            cref = _ref_of(call.func)
            if cref is None:
                continue
            donated = scope.lookup(cref)
            if not donated:
                continue
            for p in sorted(donated):
                if p >= len(call.args):
                    continue
                aref = _ref_of(call.args[p])
                if aref is None:
                    continue
                if _stores_in(stmt, aref):
                    # the call statement rebinds the reference (the
                    # `score = step(score)` carry shape) — tracking
                    # ends here
                    continue
                read = _read_before_store(later, aref)
                where = "after the call"
                if read is None:
                    # enclosing-loop rule: un-rebound carry re-reads
                    # the deleted buffer next iteration
                    for lbody in loop_bodies:
                        if not _stores_in(ast.Module(
                                body=lbody, type_ignores=[]), aref):
                            read = call.lineno
                            where = ("on the next iteration of the "
                                     "enclosing loop (the carry is "
                                     "never reassigned in its body)")
                            break
                if read is not None:
                    out.append(Finding(
                        NAME, rel, read,
                        f"{scope.name}.{_fmt(cref)}:{_fmt(aref)}",
                        f"`{_fmt(aref)}` is donated to "
                        f"`{_fmt(cref)}` (argument {p}) but read "
                        f"again {where} — the buffer is deleted at "
                        f"dispatch; reassign the reference before "
                        f"any further read (docs/perf.md "
                        f"'Iteration floor')"))
        # recurse: nested scopes see this scope's donors
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _scan_body(rel, stmt.body, _Scope(stmt.name, scope),
                       [], [], out)
        elif isinstance(stmt, ast.ClassDef):
            cscope = _Scope(stmt.name, scope, is_class=True)
            _collect_class_donors(stmt, cscope)
            _scan_body(rel, stmt.body, cscope, [], [], out)
        elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            _scan_body(rel, stmt.body, scope,
                       loop_bodies + [stmt.body], later, out)
            _scan_body(rel, stmt.orelse, scope, loop_bodies, later,
                       out)
        elif isinstance(stmt, ast.If):
            _scan_body(rel, stmt.body, scope, loop_bodies, later, out)
            _scan_body(rel, stmt.orelse, scope, loop_bodies, later,
                       out)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            _scan_body(rel, stmt.body, scope, loop_bodies, later, out)
        elif isinstance(stmt, ast.Try):
            _scan_body(rel, stmt.body, scope, loop_bodies, later, out)
            for h in stmt.handlers:
                _scan_body(rel, h.body, scope, loop_bodies, later,
                           out)
            _scan_body(rel, stmt.orelse, scope, loop_bodies, later,
                       out)
            _scan_body(rel, stmt.finalbody, scope, loop_bodies, later,
                       out)


def check(sources: SourceSet) -> List[Finding]:
    out: List[Finding] = []
    for rel, tree in sources.items():
        _scan_body(rel, tree.body, _Scope("<module>"), [], [], out)
    return out
