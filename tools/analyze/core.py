"""Shared plumbing for the drift linters: findings, sources, allowlists.

Design constraints (docs/static-analysis.md):

- stdlib-``ast`` only, zero third-party deps — the suite must run in
  any container the tests run in;
- < 10 s on the 2-core CI box: every checker works off ONE shared
  parse of the tree (:class:`SourceSet` caches the ASTs);
- every intentional exception is EXPLICIT: each checker has an
  allowlist file under ``tools/analyze/allowlists/<checker>.txt`` whose
  entries must carry a reason AND match a live finding — an unexplained
  or unused (stale) entry is itself a finding, so the allowlists cannot
  silently rot into blanket mutes.
"""
from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

ALLOWLIST_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "allowlists")


@dataclass(frozen=True)
class Finding:
    """One checker hit.

    ``key`` is the STABLE identity the allowlist matches on — never a
    line number (line-keyed suppressions rot on every unrelated edit).
    Each checker documents its key shape in docs/static-analysis.md.
    """

    checker: str
    file: str          # repo-root-relative path
    line: int
    key: str
    message: str

    def render(self) -> str:
        return (f"finding [{self.checker}] {self.file}:{self.line}: "
                f"{self.message}  (allowlist key: {self.file}:{self.key})")


@dataclass
class Allowlist:
    """Parsed ``<file>:<key>  <reason>`` entries for one checker."""

    checker: str
    entries: Dict[Tuple[str, str], str] = field(default_factory=dict)
    unexplained: List[Tuple[str, str]] = field(default_factory=list)
    used: set = field(default_factory=set)

    @classmethod
    def load(cls, checker: str,
             path: Optional[str] = None) -> "Allowlist":
        path = path or os.path.join(ALLOWLIST_DIR, f"{checker}.txt")
        al = cls(checker)
        if not os.path.exists(path):
            return al
        with open(path) as f:
            for raw in f:
                line = raw.strip()
                if not line or line.startswith("#"):
                    continue
                locator, _sep, reason = line.partition("  ")
                file, _sep2, key = locator.partition(":")
                entry = (file.strip(), key.strip())
                al.entries[entry] = reason.strip()
                if not reason.strip():
                    al.unexplained.append(entry)
        return al

    def filter(self, findings: Iterable[Finding]) -> List[Finding]:
        """Drop allowlisted findings; record which entries fired."""
        out = []
        for f in findings:
            entry = (f.file, f.key)
            if entry in self.entries:
                self.used.add(entry)
            else:
                out.append(f)
        return out

    def hygiene_findings(self) -> List[Finding]:
        """Unexplained or stale entries are findings of their own."""
        out = []
        for entry in self.unexplained:
            out.append(Finding(
                self.checker, entry[0], 0, entry[1],
                f"allowlist entry {entry[0]}:{entry[1]} has no reason "
                f"text — every exception must say why it is safe"))
        for entry, _reason in self.entries.items():
            if entry not in self.used and entry not in self.unexplained:
                out.append(Finding(
                    self.checker, entry[0], 0, entry[1],
                    f"stale allowlist entry {entry[0]}:{entry[1]} "
                    f"matches no current finding — delete it"))
        return out


class SourceSet:
    """The repo's python sources, parsed once and shared by checkers."""

    def __init__(self, root: str, rel_paths: List[str]):
        self.root = root
        self.trees: Dict[str, ast.Module] = {}
        self.texts: Dict[str, str] = {}
        self.parse_errors: List[Tuple[str, str]] = []
        for rel in rel_paths:
            full = os.path.join(root, rel)
            try:
                text = open(full, encoding="utf-8").read()
                self.trees[rel] = ast.parse(text, filename=rel)
                self.texts[rel] = text
            except (OSError, SyntaxError) as e:
                # a file that does not parse cannot be linted — surface
                # it as a finding rather than crashing the suite
                self.trees[rel] = ast.Module(body=[], type_ignores=[])
                self.texts[rel] = ""
                self.parse_errors.append((rel, str(e)))

    def items(self):
        return self.trees.items()


def discover_sources(root: str) -> List[str]:
    """Repo-relative python files the suite lints: the library, the
    benches, and the entry scripts (tests and tools lint themselves via
    their own suites)."""
    out: List[str] = []
    lib = os.path.join(root, "lightgbm_tpu")
    for dirpath, _dirs, files in os.walk(lib):
        for fn in sorted(files):
            if fn.endswith(".py"):
                out.append(os.path.relpath(os.path.join(dirpath, fn),
                                           root))
    for extra in ("bench.py", "__graft_entry__.py"):
        if os.path.exists(os.path.join(root, extra)):
            out.append(extra)
    bdir = os.path.join(root, "benchmarks")
    if os.path.isdir(bdir):
        for fn in sorted(os.listdir(bdir)):
            if fn.endswith(".py"):
                out.append(os.path.join("benchmarks", fn))
    return out


def attr_chain(node: ast.AST) -> str:
    """Dotted name of an attribute chain (``jax.lax.psum`` ->
    "jax.lax.psum"); "" when the node is not a plain chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def call_name(call: ast.Call) -> str:
    """Trailing name of a call target: ``obs.inc(...)`` -> "inc",
    ``psum(...)`` -> "psum"."""
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None
